"""Observability + trainer-config layer: graphviz dump, memory stats,
TrainerDesc/DeviceWorker factory, in-memory dataset global shuffle.

Reference: debugger.py draw_block_graphviz, scope_buffered_monitor.cc,
trainer_desc.py / device_worker.py / trainer_factory.py:26,
data_set.h:92-102 LoadIntoMemory/LocalShuffle/GlobalShuffle.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _small_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 3], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[4, 1], dtype="float32",
                        append_batch_size=False)
        loss = layers.mean(layers.square_error_cost(
            layers.fc(x, size=1), y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_program_to_dot_and_pprint(tmp_path):
    main, startup, loss = _small_program()
    dot = fluid.debugger.program_to_dot(main)
    assert "digraph" in dot and "mul" in dot and "->" in dot
    p = fluid.debugger.draw_block_graphviz(main.global_block(),
                                           path=str(tmp_path / "g.dot"))
    assert (tmp_path / "g.dot").exists()
    text = fluid.debugger.pprint_program(main)
    assert "block 0" in text and "sgd" in text


def test_scope_memory_stats():
    main, startup, loss = _small_program()
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
    stats = fluid.memory.scope_memory_stats(scope)
    assert stats["vars"] >= 2          # fc w + b at least
    assert stats["total_bytes"] > 0
    # device stats may be empty on CPU; must not raise
    fluid.memory.device_memory_stats()


def test_trainer_factory_picks_trainer():
    from paddle_tpu.trainer_desc import (DistMultiTrainer, Hogwild,
                                         MultiTrainer, TrainerFactory)
    t = TrainerFactory()._create_trainer(None)
    assert isinstance(t, MultiTrainer)
    assert isinstance(t._device_worker, Hogwild)
    t = TrainerFactory()._create_trainer(
        {"trainer": "DistMultiTrainer", "device_worker": "DownpourSGD",
         "endpoints": ["127.0.0.1:7164"], "trainer_id": 3})
    assert isinstance(t, DistMultiTrainer)
    assert t.endpoints == ["127.0.0.1:7164"] and t.trainer_id == 3


def _write_dataset(tmp_path, rows=32):
    rng = np.random.RandomState(0)
    p = tmp_path / "part-0.txt"
    with open(p, "w") as f:
        for i in range(rows):
            x = rng.randn(3)
            f.write("3 " + " ".join(f"{v:.4f}" for v in x) +
                    f" 1 {float(i):.1f}\n")
    return [str(p)]


def _mk_vars():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 3], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], dtype="float32",
                        append_batch_size=False)
    return x, y


def test_inmemory_dataset_global_shuffle_partitions(tmp_path):
    files = _write_dataset(tmp_path)
    x, y = _mk_vars()

    class _Fleet:
        def __init__(self, wid, n):
            self._wid, self._n = wid, n

        def worker_index(self):
            return self._wid

        def worker_num(self):
            return self._n

    seen = []
    for wid in range(2):
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(4)
        ds.set_use_var([x, y])
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.global_shuffle(fleet=_Fleet(wid, 2))
        ys = [float(v) for b in ds.batches(drop_last=False)
              for v in b["y"].reshape(-1)]
        seen.append(set(ys))
    # disjoint halves covering every sample exactly once
    assert seen[0].isdisjoint(seen[1])
    assert seen[0] | seen[1] == set(float(i) for i in range(32))
    assert len(seen[0]) == len(seen[1]) == 16


def test_train_from_dataset_via_trainer_factory(tmp_path):
    files = _write_dataset(tmp_path)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 3], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], dtype="float32",
                        append_batch_size=False)
        loss = layers.mean(layers.square_error_cost(
            layers.fc(x, size=1), y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_use_var([x, y])
    ds.set_filelist(files)
    ds.load_into_memory()
    ds.local_shuffle()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        last = exe.train_from_dataset(main, ds, scope=scope,
                                      fetch_list=[loss],
                                      print_period=1000)
    assert np.isfinite(np.asarray(last[0])).all()


def test_xplane_summary(tmp_path):
    """profiler.summarize_xplane aggregates the captured trace by
    category (reference print_profiler table, XPlane-based)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers, profiler

    main, startup = fluid.Program(), fluid.Program()
    sc = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(sc):
        x = layers.data("xps", shape=[32], dtype="float32")
        loss = layers.mean(layers.fc(x, size=32))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        feed = {"xps": np.ones((8, 32), np.float32)}
        exe.run(main, feed=feed, fetch_list=[loss])
        d = str(tmp_path / "trace")
        profiler.start_profiler(output_dir=d)
        exe.run(main, feed=feed, fetch_list=[loss])
        profiler.stop_profiler()
    s = profiler.summarize_xplane(d)
    assert s["total_us"] > 0 and s["by_category"] and s["top_ops"]


def test_op_error_attribution():
    """A failing lowering names the Program op, input shapes, and attrs
    (reference op_call_stack.cc PADDLE_ENFORCE attribution) instead of
    surfacing only the raw jnp traceback."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    sc = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(sc):
        x = layers.data("att_x", shape=[4], dtype="float32")
        y = layers.fc(x, size=2)
        exe = fluid.Executor()
        exe.run(startup)
        try:
            exe.run(main, feed={"att_x": np.zeros((2, 5), np.float32)},
                    fetch_list=[y])
            assert False, "expected a shape error"
        except Exception as e:
            notes = " ".join(getattr(e, "__notes__", []))
            assert "operator 'mul'" in notes and "(2, 5)" in notes, notes


def test_tools_cli_smoke(tmp_path):
    """tools/op_bench.py runs end to end on CPU (plumbing guard for
    the perf tooling; profile_step.py's summarizer is covered by
    test_xplane_summary — its full bench model is too heavy to compile
    on CPU in a unit test, and the sitecustomize pins JAX_PLATFORMS in
    subprocesses so only the tool's own --cpu flag can force CPU)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "op_bench.py"),
         "matmul", "--shape", "64x64x64", "--cpu", "--steps", "3"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "TFLOP/s" in r.stdout


# ---------------------------------------------------------------------------
# Runtime stats subsystem (paddle_tpu/monitor.py — the platform/monitor.h
# STAT registry analogue) + its executor/profiler instrumentation.
# ---------------------------------------------------------------------------

import contextlib
import json
import os
import re
import subprocess
import sys
import threading
import time


@contextlib.contextmanager
def _monitor_on():
    from paddle_tpu import monitor
    prev = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": True})
    monitor.reset_stats()
    monitor.reset_phases()
    try:
        yield monitor
    finally:
        monitor.reset_stats()
        monitor.reset_phases()
        fluid.set_flags({"FLAGS_enable_monitor": prev})


def test_monitor_counter_gauge_histogram_semantics():
    with _monitor_on() as monitor:
        monitor.STAT_ADD("t.counter")
        monitor.STAT_ADD("t.counter", 4)
        monitor.STAT_SET("t.gauge", 7)
        monitor.STAT_SET("t.gauge", 3)          # gauge keeps the latest
        for v in (0.001, 0.002, 0.004, 0.2):
            monitor.STAT_OBSERVE("t.hist", v)
        snap = monitor.get_stats_snapshot()
        assert snap["counters"]["t.counter"] == 5
        assert snap["gauges"]["t.gauge"] == 3.0
        h = snap["histograms"]["t.hist"]
        assert h["count"] == 4 and abs(h["sum"] - 0.207) < 1e-9
        assert h["min"] == 0.001 and h["max"] == 0.2
        assert 0.001 <= h["p50"] <= 0.01 and h["p95"] <= 0.2
        # kind mismatch is an error, not silent drift
        try:
            monitor.STAT_SET("t.counter", 1)
            assert False, "expected ValueError"
        except ValueError:
            pass
        # per-name and global reset (monitor.h STAT_RESET)
        monitor.STAT_RESET("t.counter")
        assert "t.counter" not in monitor.get_stats_snapshot()["counters"]
        monitor.reset_stats()
        s = monitor.get_stats_snapshot()
        assert not s["counters"] and not s["gauges"] and not s["histograms"]


def test_monitor_disabled_is_noop():
    from paddle_tpu import monitor
    prev = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": False})
    try:
        monitor.reset_stats()
        monitor.STAT_ADD("t.off_counter")
        monitor.STAT_SET("t.off_gauge", 1)
        monitor.STAT_OBSERVE("t.off_hist", 0.5)
        s = monitor.get_stats_snapshot()
        assert not s["counters"] and not s["gauges"] and not s["histograms"]
    finally:
        fluid.set_flags({"FLAGS_enable_monitor": prev})


def test_monitor_thread_safety_smoke():
    with _monitor_on() as monitor:
        n_threads, n_iter = 8, 500

        def work():
            for _ in range(n_iter):
                monitor.STAT_ADD("t.mt_counter")
                monitor.STAT_OBSERVE("t.mt_hist", 0.01)

        ts = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        snap = monitor.get_stats_snapshot()
        assert snap["counters"]["t.mt_counter"] == n_threads * n_iter
        assert snap["histograms"]["t.mt_hist"]["count"] == \
            n_threads * n_iter


def test_monitor_concurrent_mixed_exact_counts():
    """N threads hammering a MIX of STAT_ADD and STAT_OBSERVE (distinct
    per-thread increments and values) must lose nothing: exact counter
    totals, exact histogram count AND sum."""
    with _monitor_on() as monitor:
        n_threads, n_iter = 8, 400

        def work(tid):
            for i in range(n_iter):
                monitor.STAT_ADD("t.mix_counter", tid + 1)
                monitor.STAT_ADD("t.mix_counter_b")
                monitor.STAT_OBSERVE("t.mix_hist", 0.001 * (tid + 1),
                                     exemplar=f"trace-{tid}")

        ts = [threading.Thread(target=work, args=(k,))
              for k in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        snap = monitor.get_stats_snapshot()
        want_counter = n_iter * sum(k + 1 for k in range(n_threads))
        assert snap["counters"]["t.mix_counter"] == want_counter
        assert snap["counters"]["t.mix_counter_b"] == n_threads * n_iter
        h = snap["histograms"]["t.mix_hist"]
        assert h["count"] == n_threads * n_iter
        want_sum = n_iter * sum(0.001 * (k + 1) for k in range(n_threads))
        assert abs(h["sum"] - want_sum) < 1e-6
        # exemplars survive the race and surface in the snapshot
        assert any(ex.startswith("trace-")
                   for ex in h.get("exemplars", {}).values())


def test_exporter_stop_flushes_exactly_once(tmp_path):
    """stop(flush=True) writes the terminal snapshot exactly once even
    when invoked repeatedly (explicit stop + atexit both call it)."""
    with _monitor_on() as monitor:
        monitor.STAT_ADD("t.flush_counter")
        log = str(tmp_path / "flush.jsonl")
        exp = monitor.start_exporter(log, interval=60)
        assert exp is not None
        # repeat start returns the same live exporter, no second thread
        assert monitor.start_exporter(log, interval=60) is exp
        monitor.stop_exporter(flush=True)
        n1 = len(open(log).read().splitlines())
        assert n1 == 1
        # direct re-stop on the same exporter object: _flushed guard
        exp.stop(flush=True)
        exp.stop(flush=True)
        # module-level stop is now a no-op too (exporter cleared)
        monitor.stop_exporter(flush=True)
        assert len(open(log).read().splitlines()) == n1


def test_prometheus_help_lines_from_docs():
    """# HELP text is sourced from the docs/observability.md inventory:
    documented stats get a HELP line, ad-hoc test stats do not."""
    with _monitor_on() as monitor:
        help_ = monitor._stat_help()
        assert help_, "docs/observability.md inventory parsed empty"
        assert "serving.requests" in help_
        assert "trace.spans_kept" in help_
        monitor.STAT_ADD("serving.requests")
        monitor.STAT_ADD("t.undocumented_counter")
        txt = monitor.prometheus_text()
        assert ("# HELP paddle_tpu_serving_requests "
                + help_["serving.requests"]) in txt
        assert "# HELP paddle_tpu_t_undocumented_counter" not in txt
        assert "# TYPE paddle_tpu_t_undocumented_counter counter" in txt


def test_monitor_exporters(tmp_path):
    with _monitor_on() as monitor:
        monitor.STAT_ADD("t.exp_counter", 2)
        monitor.STAT_OBSERVE("t.exp_hist", 0.003)
        log = str(tmp_path / "m.jsonl")
        monitor.snapshot_to_jsonl(log)
        monitor.STAT_ADD("t.exp_counter", 1)
        monitor.snapshot_to_jsonl(log)
        lines = [json.loads(x) for x in open(log).read().splitlines()]
        assert len(lines) == 2
        assert lines[0]["kind"] == "stats_snapshot"
        assert lines[0]["counters"]["t.exp_counter"] == 2
        assert lines[1]["counters"]["t.exp_counter"] == 3  # cumulative
        txt = monitor.prometheus_text()
        assert "# TYPE paddle_tpu_t_exp_counter counter" in txt
        assert "paddle_tpu_t_exp_counter 3" in txt
        # exposition format requires +Inf (capital I), not the JSON
        # snapshot's "+inf" key
        assert 'paddle_tpu_t_exp_hist_bucket{le="+Inf"} 1' in txt
        assert '{le="+inf"}' not in txt
        assert "paddle_tpu_t_exp_hist_count 1" in txt
        prom = str(tmp_path / "m.prom")
        monitor.export_prometheus(prom)
        assert "paddle_tpu_t_exp_counter" in open(prom).read()
        # background exporter: final flush on stop appends a snapshot
        n0 = len(open(log).read().splitlines())
        monitor.start_exporter(log, interval=60)
        monitor.stop_exporter()
        assert len(open(log).read().splitlines()) == n0 + 1


def test_executor_monitor_integration():
    """Two exe.run calls on one program: 1 miss + 1 hit, step-time
    stats for both, nonzero feed bytes (the ISSUE acceptance check)."""
    main, startup, loss = _small_program()
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with _monitor_on() as monitor:
            feed = {"x": np.ones((4, 3), np.float32),
                    "y": np.zeros((4, 1), np.float32)}
            exe.run(main, feed=feed, fetch_list=[loss])
            exe.run(main, feed=feed, fetch_list=[loss])
            snap = monitor.get_stats_snapshot()
    c, h = snap["counters"], snap["histograms"]
    assert c["executor.compile_cache_miss"] == 1
    assert c["executor.compile_cache_hit"] == 1
    assert c["executor.feed_bytes"] > 0
    assert c["executor.feed_host_bytes"] > 0
    assert h["executor.step_seconds"]["count"] == 2
    assert h["executor.step_seconds"]["p50"] > 0
    assert h["executor.compile_first_step_seconds"]["count"] == 1
    assert h["executor.compile_build_seconds"]["count"] == 1
    assert h["executor.fetch_block_seconds"]["count"] == 2
    assert snap["gauges"]["executor.compile_cache_size"] >= 1


def test_reader_monitor_stats():
    with _monitor_on() as monitor:
        from paddle_tpu import reader_decorator

        def src():
            return iter(range(10))

        assert list(reader_decorator.buffered(src, 4)()) == list(range(10))
        snap = monitor.get_stats_snapshot()
        assert snap["counters"]["reader.batches"] == 10
        assert snap["histograms"]["reader.batch_wait_seconds"]["count"] \
            == 11  # 10 items + sentinel
        assert "reader.queue_depth" in snap["gauges"]


def test_record_event_nested_exclusive_and_reset():
    """Nested record_event scopes accumulate EXCLUSIVE per-phase time;
    reset_profiler actually clears the aggregates (was `pass`)."""
    from paddle_tpu import profiler
    profiler.reset_profiler()
    with profiler.record_event("outer_phase"):
        time.sleep(0.03)
        with profiler.record_event("inner_phase"):
            time.sleep(0.02)
    stats = profiler.host_phase_stats()
    assert stats["outer_phase"]["count"] == 1
    assert stats["inner_phase"]["count"] == 1
    assert stats["inner_phase"]["exclusive_s"] >= 0.015
    # outer's exclusive time excludes inner's 20ms
    assert stats["outer_phase"]["total_s"] >= 0.045
    assert stats["outer_phase"]["exclusive_s"] < \
        stats["outer_phase"]["total_s"] - 0.01
    profiler.reset_profiler()
    assert profiler.host_phase_stats() == {}


def test_monitor_chrome_trace_export(tmp_path):
    from paddle_tpu import monitor, profiler
    profiler.reset_profiler()
    with profiler.record_event("trace_phase"):
        time.sleep(0.005)
    path = str(tmp_path / "trace.json")
    n = monitor.export_chrome_tracing(path)
    assert n >= 1
    trace = json.load(open(path))
    names = [e["name"] for e in trace["traceEvents"]]
    assert "trace_phase" in names
    ev = trace["traceEvents"][names.index("trace_phase")]
    assert ev["ph"] == "X" and ev["dur"] > 0
    profiler.reset_profiler()


def test_metrics_report_cli(tmp_path):
    """tools/metrics_report.py turns a monitor JSONL into the per-phase
    breakdown table (pure stdlib — no jax import in the subprocess)."""
    main, startup, loss = _small_program()
    scope = fluid.Scope()
    exe = fluid.Executor()
    log = str(tmp_path / "run.jsonl")
    with fluid.scope_guard(scope):
        exe.run(startup)
        with _monitor_on() as monitor:
            feed = {"x": np.ones((4, 3), np.float32),
                    "y": np.zeros((4, 1), np.float32)}
            for _ in range(3):
                exe.run(main, feed=feed, fetch_list=[loss])
            monitor.STAT_SET("bench.model_flops_per_step", 1e9)
            monitor.STAT_SET("bench.peak_flops_per_chip", 197e12)
            monitor.snapshot_to_jsonl(log)
    with open(log, "a") as f:
        f.write(json.dumps({"kind": "bench_result", "metric": "m",
                            "value": 1.0, "unit": "u",
                            "vs_baseline": 0.5}) + "\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "metrics_report.py"),
         log], capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    assert "step" in out and "p50" in out and "p95" in out
    assert "hit rate" in out and "feed bytes" in out
    assert "MFU" in out
    assert "bench results" in out


def test_stat_name_lint():
    """Every stat name recorded in production code matches
    ^[a-z0-9_.]+$ AND appears in docs/observability.md — and, in the
    other direction, every name in the doc's stat-inventory table is
    still recorded somewhere in code. The registry and its documented
    inventory cannot silently drift apart either way."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pat = re.compile(r"STAT_(?:ADD|SET|OBSERVE)\(\s*[\"']([^\"']+)[\"']")
    name_re = re.compile(r"^[a-z0-9_.]+$")
    inventory = open(os.path.join(repo, "docs", "observability.md")).read()
    roots = [os.path.join(repo, "paddle_tpu"),
             os.path.join(repo, "tools"),
             os.path.join(repo, "bench.py")]
    found = set()
    corpus = []
    for root in roots:
        files = [root] if root.endswith(".py") else [
            os.path.join(dp, f) for dp, _, fs in os.walk(root)
            for f in fs if f.endswith(".py")]
        for path in files:
            text = open(path).read()
            corpus.append(text)
            for name in pat.findall(text):
                found.add((name, os.path.relpath(path, repo)))
    corpus = "\n".join(corpus)
    assert len({n for n, _ in found}) >= 10, sorted(found)
    bad = [(n, p) for n, p in found if not name_re.match(n)]
    assert not bad, f"stat names violate ^[a-z0-9_.]+$: {bad}"
    undocumented = [(n, p) for n, p in found if f"`{n}`" not in inventory]
    assert not undocumented, \
        f"stats missing from docs/observability.md inventory: {undocumented}"
    # reverse direction: documented inventory rows must still exist in
    # code (a renamed/deleted stat must drop its doc row too)
    section = inventory.split("## Stat inventory", 1)[1].split("\n## ", 1)[0]
    documented = re.findall(r"^\| `([a-z0-9_.]+)` \|", section, re.M)
    assert len(documented) >= 10, documented
    # a name passed to STAT_* via a variable (core/memory.py's stat
    # tuple) still exists as a string literal somewhere in the corpus
    code_names = {n for n, _ in found}
    stale = [n for n in documented
             if n not in code_names
             and f'"{n}"' not in corpus and f"'{n}'" not in corpus]
    assert not stale, \
        f"doc inventory rows no longer recorded anywhere in code: {stale}"


# ---------------------------------------------------------------------------
# Op-level trace attribution, NaN provenance, flight recorder,
# Prometheus scrape endpoint, bench kill-resilience (ISSUE 3).
# ---------------------------------------------------------------------------


def test_op_trace_scopes_in_compiled_hlo():
    """FLAGS_op_trace_scopes (default on) stamps every op's emission
    with '{op_type}:{block}/{op_idx}': the compiled HLO's op_name
    metadata and the debug StableHLO loc() info both carry it, and
    turning the flag off removes it (the flag is traced, so the flip
    recompiles)."""
    main, startup, loss = _small_program()
    scope = fluid.Scope()
    exe = fluid.Executor()
    feed = {"x": np.ones((4, 3), np.float32),
            "y": np.zeros((4, 1), np.float32)}
    scope_pat = re.compile(r'op_name="[^"]*\bmul:0/\d+')
    with fluid.scope_guard(scope):
        exe.run(startup)
        hlo = exe.compiled_hlo(main, feed=feed, fetch_list=[loss])
        assert scope_pat.search(hlo), hlo[:2000]
        asm = exe.lowered_mlir_debug(main, feed=feed, fetch_list=[loss])
        assert "loc(" in asm and re.search(r"mul:0/\d+", asm)
        prev = fluid.FLAGS.op_trace_scopes
        fluid.set_flags({"FLAGS_op_trace_scopes": False})
        try:
            hlo_off = exe.compiled_hlo(main, feed=feed,
                                       fetch_list=[loss])
        finally:
            fluid.set_flags({"FLAGS_op_trace_scopes": prev})
        assert not scope_pat.search(hlo_off)


def test_nan_provenance():
    """With FLAGS_check_nan_inf, the raised error names the op type,
    block/op position, output var, and input vars — and a nan_inf
    record with the same provenance lands in the flight recorder."""
    from paddle_tpu import monitor
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    prev = fluid.FLAGS.check_nan_inf
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    monitor.reset_flight_recorder()
    try:
        with fluid.program_guard(main, startup), fluid.scope_guard(scope):
            x = layers.data("nan_x", shape=[2, 2], dtype="float32",
                            append_batch_size=False)
            logged = layers.log(x)
            loss = layers.mean(logged)
            exe = fluid.Executor()
            exe.run(startup)
            try:
                exe.run(main, feed={"nan_x": np.zeros((2, 2), np.float32)},
                        fetch_list=[loss])
                assert False, "expected a nan/inf trip"
            except Exception as e:
                msg = str(e)
                assert "Operator 'log'" in msg, msg
                assert "block 0/op" in msg and "Inf/Nan" in msg, msg
                assert logged.name in msg, msg            # output var
                assert "'nan_x'" in msg, msg              # input var
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": prev})
    recs = [r for r in monitor.flight_records() if r["kind"] == "nan_inf"]
    assert recs, monitor.flight_records()
    r = recs[0]
    assert r["op_type"] == "log" and r["block"] == 0
    assert r["output"] == logged.name and r["inputs"] == ["nan_x"]
    assert r["shape"] == [2, 2] and r["n_nonfinite"] == 4
    monitor.reset_flight_recorder()


def test_flight_recorder_ring_and_dump(tmp_path):
    """Bounded ring (FLAGS_flight_recorder_capacity), executor step
    records, atomic JSONL dump with a flight_dump header, reset."""
    from paddle_tpu import monitor
    monitor.reset_flight_recorder()
    prev_cap = fluid.FLAGS.flight_recorder_capacity
    fluid.set_flags({"FLAGS_flight_recorder_capacity": 8})
    try:
        for i in range(20):
            monitor.flight_record("probe", i=i)
        recs = monitor.flight_records()
        assert len(recs) == 8                      # ring capped
        assert [r["i"] for r in recs] == list(range(12, 20))  # oldest out
        assert all(r["kind"] == "probe" and "ts" in r for r in recs)
    finally:
        fluid.set_flags({"FLAGS_flight_recorder_capacity": prev_cap})
    # executor feeds step records
    main, startup, loss = _small_program()
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = {"x": np.ones((4, 3), np.float32),
                "y": np.zeros((4, 1), np.float32)}
        exe.run(main, feed=feed, fetch_list=[loss])
        exe.run(main, feed=feed, fetch_list=[loss])
    steps = [r for r in monitor.flight_records() if r["kind"] == "step"]
    assert len(steps) >= 2
    assert steps[-1]["cache_hit"] is True and steps[0]["cache_hit"] is False
    assert steps[-1]["step_seconds"] > 0
    # with the monitor on, step records carry stats deltas
    with _monitor_on():
        with fluid.scope_guard(scope):
            exe.run(main, feed=feed, fetch_list=[loss])
        last = monitor.flight_records()[-1]
        assert last["kind"] == "step"
        assert last["stats_delta"].get("executor.feed_bytes", 0) > 0
    path = monitor.dump_flight_recorder(str(tmp_path / "fl.jsonl"),
                                        reason="unit test")
    lines = [json.loads(x) for x in open(path)]
    assert lines[0]["kind"] == "flight_dump"
    assert lines[0]["reason"] == "unit test"
    assert lines[0]["n_records"] == len(lines) - 1
    assert lines[-1]["kind"] == "step"
    # disabled -> no recording
    monitor.reset_flight_recorder()
    prev_fr = fluid.FLAGS.flight_recorder
    fluid.set_flags({"FLAGS_flight_recorder": False})
    try:
        monitor.flight_record("probe", i=0)
        assert monitor.flight_records() == []
    finally:
        fluid.set_flags({"FLAGS_flight_recorder": prev_fr})


def test_serve_prometheus_scrape():
    """monitor.serve_prometheus serves prometheus_text() over HTTP on
    127.0.0.1 and counts scrapes; port=0 binds an ephemeral port."""
    import urllib.request
    with _monitor_on() as monitor:
        monitor.STAT_ADD("t.scrape_counter", 3)
        srv = monitor.serve_prometheus(port=0)
        try:
            port = srv.server_address[1]
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
            assert "paddle_tpu_t_scrape_counter 3" in body
            snap = monitor.get_stats_snapshot()
            assert snap["counters"]["monitor.http_scrapes"] == 1
            # FLAGS_monitor_http_port=0 (default) means disabled
            assert fluid.FLAGS.monitor_http_port == 0
            assert monitor.serve_prometheus(port=None) is None
        finally:
            monitor.stop_prometheus()


def test_op_profile_attribution(tmp_path):
    """summarize_xplane(hlo_text=...) attributes trace events back to
    FRAMEWORK op types (mul, sgd, ...) — not raw HLO names — and
    tools/op_profile.py's table aggregation orders/percentages them.
    (Sized like test_xplane_summary: a smaller program executes inline
    on the calling thread and leaves no XLA trace line to attribute.)"""
    from paddle_tpu import profiler
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        x = layers.data("opp_x", shape=[32], dtype="float32")
        loss = layers.mean(layers.fc(x, size=32))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    feed = {"opp_x": np.ones((8, 32), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])  # warm/compile
        hlo = exe.compiled_hlo(main, feed=feed, fetch_list=[loss])
        d = str(tmp_path / "trace")
        profiler.start_profiler(output_dir=d)
        exe.run(main, feed=feed, fetch_list=[loss])
        profiler.stop_profiler()
    s = profiler.summarize_xplane(d, hlo_text=hlo)
    fw = s["by_framework_op"]
    types = {r["op_type"] for r in fw.values()} - {"(unattributed)"}
    assert "mul" in types, sorted(types)       # framework name, not HLO
    assert all(":" not in t or "::" in t for t in types), sorted(types)
    for key, r in fw.items():
        if key != "(unattributed)":
            assert r["calls"] >= 1 and r["total_us"] >= 0
            assert r["min_us"] <= r["max_us"]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        import op_profile
    finally:
        sys.path.pop(0)
    rows = op_profile.op_table_rows(s)
    assert rows and rows[0]["total_ms"] == max(r["total_ms"] for r in rows)
    assert abs(sum(r["pct"] for r in rows) - 100.0) < 1.0
    table = op_profile.render_table(rows, top=10)
    assert "total ms" in table and "mul" in table


def test_validate_bench_json():
    """tools/validate_bench_json.py accepts good artifacts and rejects
    the r05 failure shape (driver wrapper with parsed: null)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        import validate_bench_json as v
    finally:
        sys.path.pop(0)
    good = {"kind": "bench_summary", "status": "complete",
            "models": ["bert"], "completed": ["bert"],
            "results": [{"metric": "m", "value": 1.0, "unit": "u",
                         "vs_baseline": 0.5}],
            "ts_start": 1.0, "ts_end": 2.0}
    assert v.validate_summary(good) == []
    bad = dict(good, status="exploded", results=[{"metric": "m"}])
    errs = v.validate_summary(bad)
    assert any("status" in e for e in errs)
    assert any("missing" in e for e in errs)
    assert v.validate_wrapper({"cmd": "python bench.py", "rc": 124,
                               "parsed": None})
    assert v.validate_wrapper({"cmd": "python bench.py", "rc": 0,
                               "parsed": {"metric": "x"}}) == []


def test_bench_sigterm_leaves_parseable_artifacts(tmp_path):
    """Kill a live CPU bench run mid-measurement with SIGTERM: the
    summary JSON must parse (status killed, one result line per model)
    and the flight-recorder JSONL must exist with a flight_dump header
    and the final completed step as its last record — the r05
    rc=124/parsed:null failure can't recur."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    summary_path = tmp_path / "summary.json"
    flight_path = tmp_path / "flight.jsonl"
    log_path = tmp_path / "log.jsonl"
    env = dict(os.environ,
               BENCH_PLATFORM="cpu", BENCH_MODEL="bert",
               BENCH_LAYERS="2", BENCH_BATCH="2", BENCH_SEQ="64",
               BENCH_FLASH="0", BENCH_STEPS="2000000",
               BENCH_SUMMARY=str(summary_path),
               BENCH_FLIGHT=str(flight_path),
               BENCH_LOG=str(log_path),
               FLAGS_enable_monitor="1",
               FLAGS_monitor_flush_interval_s="0.5")
    p = subprocess.Popen([sys.executable,
                          os.path.join(repo, "bench.py")],
                         cwd=str(tmp_path), env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True)
    try:
        # wait until the exporter has flushed proof of completed steps,
        # then kill mid-measurement (compile ~15s on CPU; generous cap)
        deadline = time.time() + 240
        steps_seen = 0
        while time.time() < deadline and steps_seen < 3:
            if p.poll() is not None:
                out, err = p.communicate()
                assert False, f"bench exited early rc={p.returncode}\n" \
                              f"{out}\n{err}"
            time.sleep(0.5)
            try:
                for line in open(log_path):
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    h = rec.get("histograms", {}).get(
                        "executor.step_seconds")
                    if h:
                        steps_seen = max(steps_seen, h["count"])
            except OSError:
                continue
        assert steps_seen >= 3, "no steps observed before deadline"
        p.send_signal(15)
        out, err = p.communicate(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
            p.communicate()
    assert p.returncode == 143, f"rc={p.returncode}\n{out}\n{err}"
    # stdout: one parseable result line per model + a partial summary
    stdout_lines = [json.loads(x) for x in out.splitlines() if x.strip()]
    assert any(r.get("metric") and "killed" in r.get("error", "")
               for r in stdout_lines), out
    # summary artifact parses and is valid per the validator
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        import validate_bench_json as v
    finally:
        sys.path.pop(0)
    summary = json.load(open(summary_path))
    assert v.validate_summary(summary) == [], summary
    assert summary["status"] == "killed"
    assert summary["models"] == ["bert"] and summary["completed"] == []
    # flight recorder: header + records, last record = final step
    assert v.validate_jsonl(str(flight_path)) == []
    recs = [json.loads(x) for x in open(flight_path)]
    assert recs[0]["kind"] == "flight_dump"
    assert recs[0]["reason"] == "signal 15"
    step_recs = [r for r in recs if r["kind"] == "step"]
    assert step_recs, recs
    assert recs[-1]["kind"] == "step"
    assert recs[-1]["step"] == max(r["step"] for r in step_recs)

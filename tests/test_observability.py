"""Observability + trainer-config layer: graphviz dump, memory stats,
TrainerDesc/DeviceWorker factory, in-memory dataset global shuffle.

Reference: debugger.py draw_block_graphviz, scope_buffered_monitor.cc,
trainer_desc.py / device_worker.py / trainer_factory.py:26,
data_set.h:92-102 LoadIntoMemory/LocalShuffle/GlobalShuffle.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _small_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 3], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[4, 1], dtype="float32",
                        append_batch_size=False)
        loss = layers.mean(layers.square_error_cost(
            layers.fc(x, size=1), y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_program_to_dot_and_pprint(tmp_path):
    main, startup, loss = _small_program()
    dot = fluid.debugger.program_to_dot(main)
    assert "digraph" in dot and "mul" in dot and "->" in dot
    p = fluid.debugger.draw_block_graphviz(main.global_block(),
                                           path=str(tmp_path / "g.dot"))
    assert (tmp_path / "g.dot").exists()
    text = fluid.debugger.pprint_program(main)
    assert "block 0" in text and "sgd" in text


def test_scope_memory_stats():
    main, startup, loss = _small_program()
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
    stats = fluid.memory.scope_memory_stats(scope)
    assert stats["vars"] >= 2          # fc w + b at least
    assert stats["total_bytes"] > 0
    # device stats may be empty on CPU; must not raise
    fluid.memory.device_memory_stats()


def test_trainer_factory_picks_trainer():
    from paddle_tpu.trainer_desc import (DistMultiTrainer, Hogwild,
                                         MultiTrainer, TrainerFactory)
    t = TrainerFactory()._create_trainer(None)
    assert isinstance(t, MultiTrainer)
    assert isinstance(t._device_worker, Hogwild)
    t = TrainerFactory()._create_trainer(
        {"trainer": "DistMultiTrainer", "device_worker": "DownpourSGD",
         "endpoints": ["127.0.0.1:7164"], "trainer_id": 3})
    assert isinstance(t, DistMultiTrainer)
    assert t.endpoints == ["127.0.0.1:7164"] and t.trainer_id == 3


def _write_dataset(tmp_path, rows=32):
    rng = np.random.RandomState(0)
    p = tmp_path / "part-0.txt"
    with open(p, "w") as f:
        for i in range(rows):
            x = rng.randn(3)
            f.write("3 " + " ".join(f"{v:.4f}" for v in x) +
                    f" 1 {float(i):.1f}\n")
    return [str(p)]


def _mk_vars():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 3], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], dtype="float32",
                        append_batch_size=False)
    return x, y


def test_inmemory_dataset_global_shuffle_partitions(tmp_path):
    files = _write_dataset(tmp_path)
    x, y = _mk_vars()

    class _Fleet:
        def __init__(self, wid, n):
            self._wid, self._n = wid, n

        def worker_index(self):
            return self._wid

        def worker_num(self):
            return self._n

    seen = []
    for wid in range(2):
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(4)
        ds.set_use_var([x, y])
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.global_shuffle(fleet=_Fleet(wid, 2))
        ys = [float(v) for b in ds.batches(drop_last=False)
              for v in b["y"].reshape(-1)]
        seen.append(set(ys))
    # disjoint halves covering every sample exactly once
    assert seen[0].isdisjoint(seen[1])
    assert seen[0] | seen[1] == set(float(i) for i in range(32))
    assert len(seen[0]) == len(seen[1]) == 16


def test_train_from_dataset_via_trainer_factory(tmp_path):
    files = _write_dataset(tmp_path)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 3], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], dtype="float32",
                        append_batch_size=False)
        loss = layers.mean(layers.square_error_cost(
            layers.fc(x, size=1), y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_use_var([x, y])
    ds.set_filelist(files)
    ds.load_into_memory()
    ds.local_shuffle()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        last = exe.train_from_dataset(main, ds, scope=scope,
                                      fetch_list=[loss],
                                      print_period=1000)
    assert np.isfinite(np.asarray(last[0])).all()


def test_xplane_summary(tmp_path):
    """profiler.summarize_xplane aggregates the captured trace by
    category (reference print_profiler table, XPlane-based)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers, profiler

    main, startup = fluid.Program(), fluid.Program()
    sc = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(sc):
        x = layers.data("xps", shape=[32], dtype="float32")
        loss = layers.mean(layers.fc(x, size=32))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        feed = {"xps": np.ones((8, 32), np.float32)}
        exe.run(main, feed=feed, fetch_list=[loss])
        d = str(tmp_path / "trace")
        profiler.start_profiler(output_dir=d)
        exe.run(main, feed=feed, fetch_list=[loss])
        profiler.stop_profiler()
    s = profiler.summarize_xplane(d)
    assert s["total_us"] > 0 and s["by_category"] and s["top_ops"]


def test_op_error_attribution():
    """A failing lowering names the Program op, input shapes, and attrs
    (reference op_call_stack.cc PADDLE_ENFORCE attribution) instead of
    surfacing only the raw jnp traceback."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    sc = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(sc):
        x = layers.data("att_x", shape=[4], dtype="float32")
        y = layers.fc(x, size=2)
        exe = fluid.Executor()
        exe.run(startup)
        try:
            exe.run(main, feed={"att_x": np.zeros((2, 5), np.float32)},
                    fetch_list=[y])
            assert False, "expected a shape error"
        except Exception as e:
            notes = " ".join(getattr(e, "__notes__", []))
            assert "operator 'mul'" in notes and "(2, 5)" in notes, notes


def test_tools_cli_smoke(tmp_path):
    """tools/op_bench.py runs end to end on CPU (plumbing guard for
    the perf tooling; profile_step.py's summarizer is covered by
    test_xplane_summary — its full bench model is too heavy to compile
    on CPU in a unit test, and the sitecustomize pins JAX_PLATFORMS in
    subprocesses so only the tool's own --cpu flag can force CPU)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "op_bench.py"),
         "matmul", "--shape", "64x64x64", "--cpu", "--steps", "3"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "TFLOP/s" in r.stdout

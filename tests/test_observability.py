"""Observability + trainer-config layer: graphviz dump, memory stats,
TrainerDesc/DeviceWorker factory, in-memory dataset global shuffle.

Reference: debugger.py draw_block_graphviz, scope_buffered_monitor.cc,
trainer_desc.py / device_worker.py / trainer_factory.py:26,
data_set.h:92-102 LoadIntoMemory/LocalShuffle/GlobalShuffle.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _small_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 3], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[4, 1], dtype="float32",
                        append_batch_size=False)
        loss = layers.mean(layers.square_error_cost(
            layers.fc(x, size=1), y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_program_to_dot_and_pprint(tmp_path):
    main, startup, loss = _small_program()
    dot = fluid.debugger.program_to_dot(main)
    assert "digraph" in dot and "mul" in dot and "->" in dot
    p = fluid.debugger.draw_block_graphviz(main.global_block(),
                                           path=str(tmp_path / "g.dot"))
    assert (tmp_path / "g.dot").exists()
    text = fluid.debugger.pprint_program(main)
    assert "block 0" in text and "sgd" in text


def test_scope_memory_stats():
    main, startup, loss = _small_program()
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
    stats = fluid.memory.scope_memory_stats(scope)
    assert stats["vars"] >= 2          # fc w + b at least
    assert stats["total_bytes"] > 0
    # device stats may be empty on CPU; must not raise
    fluid.memory.device_memory_stats()


def test_trainer_factory_picks_trainer():
    from paddle_tpu.trainer_desc import (DistMultiTrainer, Hogwild,
                                         MultiTrainer, TrainerFactory)
    t = TrainerFactory()._create_trainer(None)
    assert isinstance(t, MultiTrainer)
    assert isinstance(t._device_worker, Hogwild)
    t = TrainerFactory()._create_trainer(
        {"trainer": "DistMultiTrainer", "device_worker": "DownpourSGD",
         "endpoints": ["127.0.0.1:7164"], "trainer_id": 3})
    assert isinstance(t, DistMultiTrainer)
    assert t.endpoints == ["127.0.0.1:7164"] and t.trainer_id == 3


def _write_dataset(tmp_path, rows=32):
    rng = np.random.RandomState(0)
    p = tmp_path / "part-0.txt"
    with open(p, "w") as f:
        for i in range(rows):
            x = rng.randn(3)
            f.write("3 " + " ".join(f"{v:.4f}" for v in x) +
                    f" 1 {float(i):.1f}\n")
    return [str(p)]


def _mk_vars():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 3], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], dtype="float32",
                        append_batch_size=False)
    return x, y


def test_inmemory_dataset_global_shuffle_partitions(tmp_path):
    files = _write_dataset(tmp_path)
    x, y = _mk_vars()

    class _Fleet:
        def __init__(self, wid, n):
            self._wid, self._n = wid, n

        def worker_index(self):
            return self._wid

        def worker_num(self):
            return self._n

    seen = []
    for wid in range(2):
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(4)
        ds.set_use_var([x, y])
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.global_shuffle(fleet=_Fleet(wid, 2))
        ys = [float(v) for b in ds.batches(drop_last=False)
              for v in b["y"].reshape(-1)]
        seen.append(set(ys))
    # disjoint halves covering every sample exactly once
    assert seen[0].isdisjoint(seen[1])
    assert seen[0] | seen[1] == set(float(i) for i in range(32))
    assert len(seen[0]) == len(seen[1]) == 16


def test_train_from_dataset_via_trainer_factory(tmp_path):
    files = _write_dataset(tmp_path)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 3], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], dtype="float32",
                        append_batch_size=False)
        loss = layers.mean(layers.square_error_cost(
            layers.fc(x, size=1), y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_use_var([x, y])
    ds.set_filelist(files)
    ds.load_into_memory()
    ds.local_shuffle()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        last = exe.train_from_dataset(main, ds, scope=scope,
                                      fetch_list=[loss],
                                      print_period=1000)
    assert np.isfinite(np.asarray(last[0])).all()


def test_xplane_summary(tmp_path):
    """profiler.summarize_xplane aggregates the captured trace by
    category (reference print_profiler table, XPlane-based)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers, profiler

    main, startup = fluid.Program(), fluid.Program()
    sc = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(sc):
        x = layers.data("xps", shape=[32], dtype="float32")
        loss = layers.mean(layers.fc(x, size=32))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        feed = {"xps": np.ones((8, 32), np.float32)}
        exe.run(main, feed=feed, fetch_list=[loss])
        d = str(tmp_path / "trace")
        profiler.start_profiler(output_dir=d)
        exe.run(main, feed=feed, fetch_list=[loss])
        profiler.stop_profiler()
    s = profiler.summarize_xplane(d)
    assert s["total_us"] > 0 and s["by_category"] and s["top_ops"]


def test_op_error_attribution():
    """A failing lowering names the Program op, input shapes, and attrs
    (reference op_call_stack.cc PADDLE_ENFORCE attribution) instead of
    surfacing only the raw jnp traceback."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    sc = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(sc):
        x = layers.data("att_x", shape=[4], dtype="float32")
        y = layers.fc(x, size=2)
        exe = fluid.Executor()
        exe.run(startup)
        try:
            exe.run(main, feed={"att_x": np.zeros((2, 5), np.float32)},
                    fetch_list=[y])
            assert False, "expected a shape error"
        except Exception as e:
            notes = " ".join(getattr(e, "__notes__", []))
            assert "operator 'mul'" in notes and "(2, 5)" in notes, notes


def test_tools_cli_smoke(tmp_path):
    """tools/op_bench.py runs end to end on CPU (plumbing guard for
    the perf tooling; profile_step.py's summarizer is covered by
    test_xplane_summary — its full bench model is too heavy to compile
    on CPU in a unit test, and the sitecustomize pins JAX_PLATFORMS in
    subprocesses so only the tool's own --cpu flag can force CPU)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "op_bench.py"),
         "matmul", "--shape", "64x64x64", "--cpu", "--steps", "3"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "TFLOP/s" in r.stdout


# ---------------------------------------------------------------------------
# Runtime stats subsystem (paddle_tpu/monitor.py — the platform/monitor.h
# STAT registry analogue) + its executor/profiler instrumentation.
# ---------------------------------------------------------------------------

import contextlib
import json
import os
import re
import subprocess
import sys
import threading
import time


@contextlib.contextmanager
def _monitor_on():
    from paddle_tpu import monitor
    prev = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": True})
    monitor.reset_stats()
    monitor.reset_phases()
    try:
        yield monitor
    finally:
        monitor.reset_stats()
        monitor.reset_phases()
        fluid.set_flags({"FLAGS_enable_monitor": prev})


def test_monitor_counter_gauge_histogram_semantics():
    with _monitor_on() as monitor:
        monitor.STAT_ADD("t.counter")
        monitor.STAT_ADD("t.counter", 4)
        monitor.STAT_SET("t.gauge", 7)
        monitor.STAT_SET("t.gauge", 3)          # gauge keeps the latest
        for v in (0.001, 0.002, 0.004, 0.2):
            monitor.STAT_OBSERVE("t.hist", v)
        snap = monitor.get_stats_snapshot()
        assert snap["counters"]["t.counter"] == 5
        assert snap["gauges"]["t.gauge"] == 3.0
        h = snap["histograms"]["t.hist"]
        assert h["count"] == 4 and abs(h["sum"] - 0.207) < 1e-9
        assert h["min"] == 0.001 and h["max"] == 0.2
        assert 0.001 <= h["p50"] <= 0.01 and h["p95"] <= 0.2
        # kind mismatch is an error, not silent drift
        try:
            monitor.STAT_SET("t.counter", 1)
            assert False, "expected ValueError"
        except ValueError:
            pass
        # per-name and global reset (monitor.h STAT_RESET)
        monitor.STAT_RESET("t.counter")
        assert "t.counter" not in monitor.get_stats_snapshot()["counters"]
        monitor.reset_stats()
        s = monitor.get_stats_snapshot()
        assert not s["counters"] and not s["gauges"] and not s["histograms"]


def test_monitor_disabled_is_noop():
    from paddle_tpu import monitor
    prev = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": False})
    try:
        monitor.reset_stats()
        monitor.STAT_ADD("t.off_counter")
        monitor.STAT_SET("t.off_gauge", 1)
        monitor.STAT_OBSERVE("t.off_hist", 0.5)
        s = monitor.get_stats_snapshot()
        assert not s["counters"] and not s["gauges"] and not s["histograms"]
    finally:
        fluid.set_flags({"FLAGS_enable_monitor": prev})


def test_monitor_thread_safety_smoke():
    with _monitor_on() as monitor:
        n_threads, n_iter = 8, 500

        def work():
            for _ in range(n_iter):
                monitor.STAT_ADD("t.mt_counter")
                monitor.STAT_OBSERVE("t.mt_hist", 0.01)

        ts = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        snap = monitor.get_stats_snapshot()
        assert snap["counters"]["t.mt_counter"] == n_threads * n_iter
        assert snap["histograms"]["t.mt_hist"]["count"] == \
            n_threads * n_iter


def test_monitor_exporters(tmp_path):
    with _monitor_on() as monitor:
        monitor.STAT_ADD("t.exp_counter", 2)
        monitor.STAT_OBSERVE("t.exp_hist", 0.003)
        log = str(tmp_path / "m.jsonl")
        monitor.snapshot_to_jsonl(log)
        monitor.STAT_ADD("t.exp_counter", 1)
        monitor.snapshot_to_jsonl(log)
        lines = [json.loads(x) for x in open(log).read().splitlines()]
        assert len(lines) == 2
        assert lines[0]["kind"] == "stats_snapshot"
        assert lines[0]["counters"]["t.exp_counter"] == 2
        assert lines[1]["counters"]["t.exp_counter"] == 3  # cumulative
        txt = monitor.prometheus_text()
        assert "# TYPE paddle_tpu_t_exp_counter counter" in txt
        assert "paddle_tpu_t_exp_counter 3" in txt
        assert 'paddle_tpu_t_exp_hist_bucket{le="+inf"} 1' in txt
        assert "paddle_tpu_t_exp_hist_count 1" in txt
        prom = str(tmp_path / "m.prom")
        monitor.export_prometheus(prom)
        assert "paddle_tpu_t_exp_counter" in open(prom).read()
        # background exporter: final flush on stop appends a snapshot
        n0 = len(open(log).read().splitlines())
        monitor.start_exporter(log, interval=60)
        monitor.stop_exporter()
        assert len(open(log).read().splitlines()) == n0 + 1


def test_executor_monitor_integration():
    """Two exe.run calls on one program: 1 miss + 1 hit, step-time
    stats for both, nonzero feed bytes (the ISSUE acceptance check)."""
    main, startup, loss = _small_program()
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with _monitor_on() as monitor:
            feed = {"x": np.ones((4, 3), np.float32),
                    "y": np.zeros((4, 1), np.float32)}
            exe.run(main, feed=feed, fetch_list=[loss])
            exe.run(main, feed=feed, fetch_list=[loss])
            snap = monitor.get_stats_snapshot()
    c, h = snap["counters"], snap["histograms"]
    assert c["executor.compile_cache_miss"] == 1
    assert c["executor.compile_cache_hit"] == 1
    assert c["executor.feed_bytes"] > 0
    assert c["executor.feed_host_bytes"] > 0
    assert h["executor.step_seconds"]["count"] == 2
    assert h["executor.step_seconds"]["p50"] > 0
    assert h["executor.compile_first_step_seconds"]["count"] == 1
    assert h["executor.compile_build_seconds"]["count"] == 1
    assert h["executor.fetch_block_seconds"]["count"] == 2
    assert snap["gauges"]["executor.compile_cache_size"] >= 1


def test_reader_monitor_stats():
    with _monitor_on() as monitor:
        from paddle_tpu import reader_decorator

        def src():
            return iter(range(10))

        assert list(reader_decorator.buffered(src, 4)()) == list(range(10))
        snap = monitor.get_stats_snapshot()
        assert snap["counters"]["reader.batches"] == 10
        assert snap["histograms"]["reader.batch_wait_seconds"]["count"] \
            == 11  # 10 items + sentinel
        assert "reader.queue_depth" in snap["gauges"]


def test_record_event_nested_exclusive_and_reset():
    """Nested record_event scopes accumulate EXCLUSIVE per-phase time;
    reset_profiler actually clears the aggregates (was `pass`)."""
    from paddle_tpu import profiler
    profiler.reset_profiler()
    with profiler.record_event("outer_phase"):
        time.sleep(0.03)
        with profiler.record_event("inner_phase"):
            time.sleep(0.02)
    stats = profiler.host_phase_stats()
    assert stats["outer_phase"]["count"] == 1
    assert stats["inner_phase"]["count"] == 1
    assert stats["inner_phase"]["exclusive_s"] >= 0.015
    # outer's exclusive time excludes inner's 20ms
    assert stats["outer_phase"]["total_s"] >= 0.045
    assert stats["outer_phase"]["exclusive_s"] < \
        stats["outer_phase"]["total_s"] - 0.01
    profiler.reset_profiler()
    assert profiler.host_phase_stats() == {}


def test_monitor_chrome_trace_export(tmp_path):
    from paddle_tpu import monitor, profiler
    profiler.reset_profiler()
    with profiler.record_event("trace_phase"):
        time.sleep(0.005)
    path = str(tmp_path / "trace.json")
    n = monitor.export_chrome_tracing(path)
    assert n >= 1
    trace = json.load(open(path))
    names = [e["name"] for e in trace["traceEvents"]]
    assert "trace_phase" in names
    ev = trace["traceEvents"][names.index("trace_phase")]
    assert ev["ph"] == "X" and ev["dur"] > 0
    profiler.reset_profiler()


def test_metrics_report_cli(tmp_path):
    """tools/metrics_report.py turns a monitor JSONL into the per-phase
    breakdown table (pure stdlib — no jax import in the subprocess)."""
    main, startup, loss = _small_program()
    scope = fluid.Scope()
    exe = fluid.Executor()
    log = str(tmp_path / "run.jsonl")
    with fluid.scope_guard(scope):
        exe.run(startup)
        with _monitor_on() as monitor:
            feed = {"x": np.ones((4, 3), np.float32),
                    "y": np.zeros((4, 1), np.float32)}
            for _ in range(3):
                exe.run(main, feed=feed, fetch_list=[loss])
            monitor.STAT_SET("bench.model_flops_per_step", 1e9)
            monitor.STAT_SET("bench.peak_flops_per_chip", 197e12)
            monitor.snapshot_to_jsonl(log)
    with open(log, "a") as f:
        f.write(json.dumps({"kind": "bench_result", "metric": "m",
                            "value": 1.0, "unit": "u",
                            "vs_baseline": 0.5}) + "\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "metrics_report.py"),
         log], capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    assert "step" in out and "p50" in out and "p95" in out
    assert "hit rate" in out and "feed bytes" in out
    assert "MFU" in out
    assert "bench results" in out


def test_stat_name_lint():
    """Every stat name recorded in production code matches
    ^[a-z0-9_.]+$ AND appears in docs/observability.md — the registry
    cannot silently drift from its documented inventory."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pat = re.compile(r"STAT_(?:ADD|SET|OBSERVE)\(\s*[\"']([^\"']+)[\"']")
    name_re = re.compile(r"^[a-z0-9_.]+$")
    inventory = open(os.path.join(repo, "docs", "observability.md")).read()
    roots = [os.path.join(repo, "paddle_tpu"),
             os.path.join(repo, "tools"),
             os.path.join(repo, "bench.py")]
    found = set()
    for root in roots:
        files = [root] if root.endswith(".py") else [
            os.path.join(dp, f) for dp, _, fs in os.walk(root)
            for f in fs if f.endswith(".py")]
        for path in files:
            for name in pat.findall(open(path).read()):
                found.add((name, os.path.relpath(path, repo)))
    assert len({n for n, _ in found}) >= 10, sorted(found)
    bad = [(n, p) for n, p in found if not name_re.match(n)]
    assert not bad, f"stat names violate ^[a-z0-9_.]+$: {bad}"
    undocumented = [(n, p) for n, p in found if f"`{n}`" not in inventory]
    assert not undocumented, \
        f"stats missing from docs/observability.md inventory: {undocumented}"

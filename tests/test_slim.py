"""slim: structured pruning + distillation (reference contrib/slim/
prune/pruner.py, distillation/distiller.py). Quantization is covered in
test_jit_and_extras.py."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.contrib.slim.distillation import (L2Distiller,
                                                  SoftLabelDistiller,
                                                  FSPDistiller, merge)
from paddle_tpu.contrib.slim.prune import StructurePruner, prune_program

rng = np.random.RandomState(7)


def _toy_data(n=64):
    x = rng.randn(n, 8).astype(np.float32)
    w_true = rng.randn(8, 1).astype(np.float32)
    y = (x @ w_true + 0.1 * rng.randn(n, 1)).astype(np.float32)
    return x, y


def _build_mlp(hidden=16, prefix=""):
    x = layers.data("x", shape=[-1, 8], dtype="float32",
                    append_batch_size=False)
    y = layers.data("y", shape=[-1, 1], dtype="float32",
                    append_batch_size=False)
    from paddle_tpu.framework import ParamAttr
    h = layers.fc(x, size=hidden, act="relu",
                  param_attr=ParamAttr(name=f"{prefix}fc1.w"),
                  bias_attr=ParamAttr(name=f"{prefix}fc1.b"))
    pred = layers.fc(h, size=1,
                     param_attr=ParamAttr(name=f"{prefix}fc2.w"),
                     bias_attr=ParamAttr(name=f"{prefix}fc2.b"))
    loss = layers.mean(layers.square_error_cost(pred, y))
    return loss, pred, h


def test_structure_pruner_idx_and_tensor():
    p = StructurePruner({"*": 0}, {"*": "l1_norm"})
    w = np.array([[3.0, 3.0], [0.1, 0.1], [2.0, 2.0], [0.2, 0.2]],
                 np.float32)
    idx = p.cal_pruned_idx("w", w, 0.5, axis=0)
    assert set(idx.tolist()) == {1, 3}  # two smallest l1 rows
    shr = p.prune_tensor(w, idx, 0, lazy=False)
    assert shr.shape == (2, 2) and shr[0, 0] == 3.0
    msk = p.prune_tensor(w, idx, 0, lazy=True)
    assert msk.shape == w.shape and msk[1].sum() == 0 and msk[0, 0] == 3.0


def _train(exe, prog, feed, loss, steps, scope):
    with fluid.scope_guard(scope):
        for _ in range(steps):
            lv, = exe.run(prog, feed=feed, fetch_list=[loss])
    return float(lv)


def test_prune_then_finetune_recovers():
    """Mask-prune 50% of hidden units, then finetune: loss recovers
    (reference prune_strategy sensitivity loop, collapsed to one shot)."""
    x, y = _toy_data()
    feed = {"x": x, "y": y}
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        loss, pred, h = _build_mlp()
        fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
    trained = _train(exe, main, feed, loss, 60, scope)

    pruned = prune_program(main, scope, ["fc1.w"], [0.5], lazy=True)
    assert len(pruned["fc1.w"]) == 8   # half of 16 hidden units
    # pruned columns of fc1.w and matching rows of fc2.w are zero
    w1 = scope.get_numpy("fc1.w")
    w2 = scope.get_numpy("fc2.w")
    assert np.allclose(w1[:, pruned["fc1.w"]], 0)
    assert np.allclose(w2[pruned["fc1.w"], :], 0)

    after_prune = _train(exe, main, feed, loss, 1, scope)
    finetuned = _train(exe, main, feed, loss, 60, scope)
    assert finetuned <= after_prune + 1e-6
    assert finetuned < trained * 3 + 0.05, \
        (trained, after_prune, finetuned)


def test_prune_shrink_rewrites_shapes():
    """Shrink mode physically slices params + rewrites var shapes; the
    smaller program still runs."""
    x, y = _toy_data(16)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        loss, pred, h = _build_mlp()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
    prune_program(main, scope, ["fc1.w"], [0.25], lazy=False)
    assert scope.get_numpy("fc1.w").shape == (8, 12)
    assert scope.get_numpy("fc1.b").shape == (12,)
    assert scope.get_numpy("fc2.w").shape == (12, 1)
    assert main.global_block().var("fc1.w").shape == [8, 12]
    with fluid.scope_guard(scope):
        lv, = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
    assert np.isfinite(lv).all()


def test_distillation_student_learns_from_teacher():
    """Teacher-program merge + KD losses: the student's combined loss
    (task + L2 + soft-label) decreases and the KD term shrinks."""
    x, y = _toy_data()
    feed = {"x": x, "y": y}

    # train a teacher
    t_main, t_startup = fluid.Program(), fluid.Program()
    t_scope = fluid.Scope()
    with fluid.program_guard(t_main, t_startup):
        t_loss, t_pred, t_h = _build_mlp(hidden=32)
        fluid.optimizer.Adam(learning_rate=0.02).minimize(t_loss)
    exe = fluid.Executor()
    with fluid.scope_guard(t_scope):
        exe.run(t_startup)
    _train(exe, t_main, feed, t_loss, 80, t_scope)
    t_infer = t_main.clone(for_test=True)

    # student + merged teacher; minimize under the student startup so
    # accumulator inits land there, run once AFTER graph construction
    s_main, s_startup = fluid.Program(), fluid.Program()
    s_scope = fluid.Scope()
    with fluid.program_guard(s_main, s_startup):
        s_loss, s_pred, s_h = _build_mlp(hidden=8)
    merge(t_infer, s_main, data_name_map={"x": "x", "y": "y"},
          scope=s_scope, teacher_scope=t_scope)

    l2 = L2Distiller(s_pred.name, t_pred.name,
                     distillation_loss_weight=1.0)
    kd_loss = l2.distiller_loss(s_main)
    with fluid.program_guard(s_main, s_startup):
        total = fluid.layers.elementwise_add(s_loss, kd_loss)
        fluid.optimizer.Adam(learning_rate=0.02).minimize(total)

    with fluid.scope_guard(s_scope):
        exe.run(s_startup)
        first = exe.run(s_main, feed=feed,
                        fetch_list=[total, kd_loss])
        for _ in range(60):
            last = exe.run(s_main, feed=feed,
                           fetch_list=[total, kd_loss])
    assert float(last[0]) < float(first[0])
    assert float(last[1]) < float(first[1])
    # teacher weights must not have been trained by the student step
    np.testing.assert_allclose(
        s_scope.get_numpy("teacher_fc1.w"), t_scope.get_numpy("fc1.w"))


def test_soft_label_and_fsp_distillers_build():
    x, _ = _toy_data(8)
    t_main, t_startup = fluid.Program(), fluid.Program()
    t_scope = fluid.Scope()
    with fluid.program_guard(t_main, t_startup):
        t_loss, t_pred, t_h = _build_mlp(hidden=8)
    exe = fluid.Executor()
    with fluid.scope_guard(t_scope):
        exe.run(t_startup)

    s_main, s_startup = fluid.Program(), fluid.Program()
    s_scope = fluid.Scope()
    with fluid.program_guard(s_main, s_startup):
        s_loss, s_pred, s_h = _build_mlp(hidden=8)
    with fluid.scope_guard(s_scope):
        exe.run(s_startup)
    merge(t_main.clone(for_test=True), s_main,
          data_name_map={"x": "x", "y": "y"}, scope=s_scope,
          teacher_scope=t_scope)
    sl = SoftLabelDistiller(s_pred.name, t_pred.name,
                            student_temperature=2.0,
                            teacher_temperature=2.0)
    sl_loss = sl.distiller_loss(s_main)

    # fsp wants [N, C, H, W] maps: lift hidden/pred to 4D via reshape
    with fluid.program_guard(s_main):
        s4a = layers.reshape(s_main.global_block().var(s_h.name),
                             [-1, 8, 1, 1])
        s4b = layers.reshape(s_main.global_block().var(s_pred.name),
                             [-1, 1, 1, 1])
        t4a = layers.reshape(
            s_main.global_block().var("teacher_" + t_h.name),
            [-1, 8, 1, 1])
        t4b = layers.reshape(
            s_main.global_block().var("teacher_" + t_pred.name),
            [-1, 1, 1, 1])
    # the lifted teacher maps are student-program vars already — the
    # distiller resolves them directly (no PREFIX re-application)
    fsp = FSPDistiller([(s4a.name, s4b.name)], [(t4a.name, t4b.name)])
    fsp_loss = fsp.distiller_loss(s_main)

    y = np.zeros((8, 1), np.float32)
    with fluid.scope_guard(s_scope):
        out, fsp_out = exe.run(s_main, feed={"x": x, "y": y},
                               fetch_list=[sl_loss, fsp_loss])
    assert np.isfinite(out).all() and np.isfinite(fsp_out).all()


def test_sa_controller_anneals_toward_best():
    from paddle_tpu.contrib.slim.nas import SAController

    ctl = SAController(seed=3, init_temperature=1.0, reduce_rate=0.5)
    # reward = -(distance from target tokens): optimum at [2, 2, 2]
    ctl.reset([4, 4, 4], init_tokens=[0, 0, 0])
    for _ in range(60):
        toks = ctl.next_tokens()
        reward = -sum(abs(t - 2) for t in toks)
        ctl.update(toks, reward)
    assert ctl.max_reward >= -1, (ctl.best_tokens, ctl.max_reward)


def test_light_nas_searches_hidden_width():
    """NAS over fc width: wider nets fit the toy data better, so the
    search must move toward larger widths within the flops budget."""
    from paddle_tpu.contrib.slim.nas import LightNAS, SearchSpace

    x, y = _toy_data(32)
    widths = [2, 4, 8, 16]

    class WidthSpace(SearchSpace):
        def init_tokens(self):
            return [0]

        def range_table(self):
            return [len(widths)]

        def flops(self, tokens):
            return widths[tokens[0]] * 8 * 2

        def create_net(self, tokens):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                loss, pred, h = _build_mlp(hidden=widths[tokens[0]])
                fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
            return startup, main, loss

    # budget excludes width 16: the constraint must actually bind
    budget = 8 * 8 * 2
    nas = LightNAS(WidthSpace(), search_steps=6, train_steps=15,
                   max_flops=budget, seed=0)
    best, reward = nas.search([{"x": x, "y": y}])
    assert len(nas.history) == 6
    assert np.isfinite(reward)
    assert all(WidthSpace().flops(t) <= budget for t, _ in nas.history)
    assert WidthSpace().flops(best) <= budget
    # over-budget init tokens must be refused loudly
    import pytest as _pytest

    class BadInit(WidthSpace):
        def init_tokens(self):
            return [3]  # width 16 > budget

    with _pytest.raises(ValueError, match="constraint"):
        LightNAS(BadInit(), search_steps=1, max_flops=budget)

"""Goodput accounting (paddle_tpu/goodput.py): the category-sum ≈
wall-clock invariant on a real CPU training run (and the double-count
failure mode it exists to catch), input-starvation under a
slow_step:site=reader fault — input_wait must dominate the ledger and
the auto-installed burn-rate alert must fire exactly once with exactly
one incident bundle — TrainerGuard / RetryPolicy category attribution,
serving busy/idle counters, and the tools/goodput_report.py CLI
round-trip through the JSON validator, the perf ledger, and
metrics_report."""
import contextlib
import glob
import io
import json
import os
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import goodput, layers, monitor, monitor_alerts
from paddle_tpu.core.flags import FLAGS
from paddle_tpu.monitor_alerts import AlertEngine, parse_rules
from paddle_tpu.resilience import RetryPolicy, TrainerGuard, \
    TransientFault, reset_injector
from paddle_tpu.resilience.trainer_guard import PreemptedError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tools(module):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(module)
    finally:
        sys.path.pop(0)


@pytest.fixture(autouse=True)
def _goodput_hygiene():
    """No test may leak a live ledger, an armed fault, or an appended
    alert rule into the rest of the suite."""
    yield
    goodput.reset()
    monitor_alerts.stop_alerts()
    monitor.reset_stats()
    fluid.set_flags({"FLAGS_enable_goodput": False,
                     "FLAGS_enable_monitor": False,
                     "FLAGS_alert_rules": "",
                     "FLAGS_fault_spec": "",
                     "FLAGS_fault_seed": 0})
    reset_injector()


@contextlib.contextmanager
def _goodput_on(**flag_over):
    keys = list(flag_over) + ["enable_monitor", "enable_goodput",
                              "alert_rules"]
    prev = {k: getattr(FLAGS, k) for k in keys}
    fluid.set_flags({"FLAGS_enable_monitor": True,
                     "FLAGS_enable_goodput": True,
                     **{f"FLAGS_{k}": v for k, v in flag_over.items()}})
    monitor.reset_stats()
    try:
        yield
    finally:
        goodput.reset()
        monitor.reset_stats()
        fluid.set_flags({f"FLAGS_{k}": v for k, v in prev.items()})


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _build_sgd():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), \
            fluid.unique_name.guard("gpt_"):
        x = layers.data("x", shape=[-1, 3], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], dtype="float32",
                        append_batch_size=False)
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _clean_batch(seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(4, 3).astype(np.float32),
            "y": rng.randn(4, 1).astype(np.float32)}


def _nan_batch():
    b = _clean_batch(1)
    b["x"] = b["x"].copy()
    b["x"][0, 0] = np.nan
    return b


# ---------------------------------------------------------------------------
# Off switch + basic ledger semantics
# ---------------------------------------------------------------------------

def test_disabled_is_total_noop():
    assert goodput.start_run("off") is None
    assert goodput.active() is None
    goodput.attribute("device_compute", 1.0)   # must not raise
    goodput.note_input_wait(1.0)
    goodput.serving_busy(1.0)
    assert goodput.snapshot() is None
    assert goodput.end_run() is None


def test_invariant_residual_vs_double_count():
    """`other` absorbs unattributed wall (sum == wall, invariant
    holds); double counting pushes the sum past wall and the invariant
    catches it via sum_frac_err."""
    with _goodput_on():
        led = goodput.start_run("inv")
        assert led is not None
        time.sleep(0.02)
        snap = goodput.end_run()
        assert set(snap["categories"]) == set(goodput.CATEGORIES)
        # nothing attributed -> everything is residual `other`
        assert snap["categories"]["other"] == pytest.approx(
            snap["wall_s"], rel=1e-6)
        assert goodput.check_invariant(snap)

        # over-attribution: categories now sum way past wall-clock
        goodput.attribute("device_compute", 10.0 * snap["wall_s"])
        bad = goodput.snapshot()
        assert bad["sum_frac_err"] > 1.0
        assert not goodput.check_invariant(bad)


def test_starved_step_counter_thresholds():
    with _goodput_on(goodput_starved_ms=20.0):
        goodput.start_run("thresh")
        goodput.note_input_wait(0.001)   # 1ms: fed
        goodput.note_input_wait(0.050)   # 50ms: starved
        snap = goodput.end_run()
        assert snap["input_batches"] == 2
        assert snap["starved_steps"] == 1
        c = monitor.get_stats_snapshot()["counters"]
        assert c["goodput.input_batches"] == 2
        assert c["goodput.input_starved_steps"] == 1


def test_serving_counters_feed_the_registry():
    with _goodput_on():
        goodput.start_run("serve")
        goodput.serving_busy(0.4)
        goodput.serving_idle(0.6)
        goodput.serving_pad_waste(0.1)
        goodput.gen_busy(0.2)
        goodput.gen_idle(0.3)
        c = monitor.get_stats_snapshot()["counters"]
        assert c["goodput.serving_busy_seconds"] == pytest.approx(0.4)
        assert c["goodput.serving_idle_seconds"] == pytest.approx(0.6)
        assert c["goodput.serving_pad_waste_seconds"] == \
            pytest.approx(0.1)
        assert c["goodput.gen_busy_seconds"] == pytest.approx(0.2)
        assert c["goodput.gen_idle_seconds"] == pytest.approx(0.3)


# ---------------------------------------------------------------------------
# Real training runs (CPU smoke): invariant, warmup, starvation
# ---------------------------------------------------------------------------

def test_smoke_clean_run_sums_to_wall_clock():
    gr = _tools("goodput_report")
    snap = gr.run_smoke(steps=8, batch=4, label="t_clean")
    assert snap["steps"] == 8
    assert goodput.check_invariant(snap, tol=0.05)
    # exactly one compile (the first dispatch); zero after warmup
    assert snap["compile_steps"] >= 1
    assert snap["post_warmup_compiles"] == 0
    assert 0.0 < snap["goodput_frac"] <= 1.0
    assert snap["categories"]["compile"] > 0.0
    assert snap["categories"]["device_compute"] > 0.0


def test_starved_smoke_input_wait_dominates():
    """The ISSUE acceptance demo: under slow_step:site=reader the
    ledger must pin the blame on input_wait, not smear it into
    other/compute."""
    gr = _tools("goodput_report")
    snap = gr.run_smoke(steps=8, batch=4, starve=True, starve_ms=50.0,
                        label="t_starved")
    assert goodput.check_invariant(snap, tol=0.05)
    cats = snap["categories"]
    top = max(cats, key=lambda k: cats[k])
    assert top == "input_wait", cats
    assert cats["input_wait"] >= 0.5 * snap["wall_s"]
    assert snap["starved_steps"] == 8
    # the waterfall records carry the per-step wait for the report
    waits = [r["input_wait_s"] for r in snap["step_records"]]
    assert max(waits) >= 0.04


# ---------------------------------------------------------------------------
# Starvation alert: exactly one firing, exactly one incident bundle
# ---------------------------------------------------------------------------

def test_starvation_alert_fires_once_with_one_bundle(tmp_path):
    """start_run auto-installs the input_starvation burn rule; a real
    reader under slow_step:site=reader must trip it exactly once (one
    pending->firing episode == one incident bundle), and healthy
    warmup traffic must not."""
    with _goodput_on(goodput_starved_ms=20.0,
                     goodput_alert_windows="5s,15s",
                     alert_bundle_dir=str(tmp_path),
                     alert_rules=""):
        goodput.start_run("alerting")
        assert "input_starvation" in FLAGS.alert_rules
        clock = _Clock()
        eng = AlertEngine(parse_rules(FLAGS.alert_rules), clock=clock)

        # healthy warmup: 2ms waits, enough ticks to cover both windows
        for _ in range(5):
            for _ in range(20):
                goodput.note_input_wait(0.002)
            eng.evaluate_once()
            clock.t += 5
        out = eng.evaluate_once()
        r = out["rules"][0]
        assert out["firing"] == 0
        assert all(w["covered"] for w in r["window_detail"].values())

        # starve: a real DataLoader whose reader site stalls ~30ms
        fluid.set_flags(
            {"FLAGS_fault_spec": "slow_step:ms=30:site=reader"})
        reset_injector()

        def _drain_batches(n):
            loader = fluid.io.DataLoader.from_generator(capacity=2)
            loader.set_batch_generator(
                lambda: iter([{"i": k} for k in range(n)]))
            for _ in loader():
                pass

        fired_tick = None
        for tick in range(5):
            _drain_batches(10)
            clock.t += 5
            out = eng.evaluate_once()
            if out["firing"] and fired_tick is None:
                fired_tick = tick
        assert fired_tick is not None, out
        assert out["firing"] == 1

        c = monitor.get_stats_snapshot()["counters"]
        assert c["alerts.fired"] == 1
        assert c["goodput.input_starved_steps"] >= 10
        bundles = sorted(glob.glob(
            str(tmp_path / "incident_input_starvation_*.json")))
        assert len(bundles) == 1, bundles
        with open(bundles[0]) as f:
            bundle = json.load(f)
        assert bundle["rule"]["name"] == "input_starvation"
        validate = _tools("validate_bench_json").validate_incident_bundle
        assert validate(bundle, bundles[0]) == []

        # the ledger agrees with the alert: waits landed in input_wait
        snap = goodput.end_run()
        assert snap["categories"]["input_wait"] > 0.0


def test_start_run_does_not_duplicate_rule():
    with _goodput_on(alert_rules=""):
        goodput.start_run("a")
        once = FLAGS.alert_rules
        goodput.reset()
        goodput.start_run("b")
        assert FLAGS.alert_rules == once
        assert once.count("input_starvation") == 1


# ---------------------------------------------------------------------------
# Resilience-path attribution
# ---------------------------------------------------------------------------

def test_retry_backoff_attribution():
    with _goodput_on():
        goodput.start_run("retry")
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise TransientFault("transient")
            return "ok"

        pol = RetryPolicy(max_attempts=5, base_delay_ms=40.0,
                          max_delay_ms=40.0, sleep=lambda s: None)
        assert pol.call(flaky) == "ok"
        snap = goodput.end_run()
        # two backoffs were attributed even though the sleep was faked
        assert snap["categories"]["retry_backoff"] >= 0.04


def test_trainer_guard_checkpoint_restore_and_rollback(tmp_path):
    main, startup, loss = _build_sgd()
    scope = fluid.Scope()
    ckpt = str(tmp_path / "ckpt")
    with fluid.scope_guard(scope), _goodput_on():
        exe = fluid.Executor()
        exe.run(startup)
        goodput.start_run("guard")
        guard = TrainerGuard(exe, main, scope=scope, fetch_list=[loss],
                             checkpoint_dir=ckpt,
                             install_sigterm=False)
        try:
            assert guard.step(_clean_batch()) is not None
            guard.checkpoint()
            led = goodput.active()
            assert led.category_seconds("checkpoint_save") > 0.0
            assert led.category_seconds("preempt_drain") == 0.0

            assert guard.step(_nan_batch()) is None   # rollback path
            assert led.category_seconds("nan_rollback") > 0.0

            guard.resume()
            assert led.category_seconds("checkpoint_restore") > 0.0

            # preemption drain is its own category, not checkpoint_save
            save_before = led.category_seconds("checkpoint_save")
            guard.request_preemption()
            with pytest.raises(PreemptedError):
                guard.step(_clean_batch(2))
            assert led.category_seconds("preempt_drain") > 0.0
            assert led.category_seconds("checkpoint_save") == \
                pytest.approx(save_before)
        finally:
            guard.close()


# ---------------------------------------------------------------------------
# Report CLI round-trip: validator, perf ledger, metrics_report
# ---------------------------------------------------------------------------

def test_report_cli_roundtrip(tmp_path):
    gr = _tools("goodput_report")
    out = str(tmp_path / "gp.jsonl")
    rc = gr.main(["--smoke", "--steps", "6", "--batch", "4",
                  "--config", "t_roundtrip", "--check", "--out", out])
    assert rc == 0

    vb = _tools("validate_bench_json")
    assert vb.validate_file(out) == []

    recs = [json.loads(l) for l in open(out) if l.strip()]
    rep = [r for r in recs if r.get("kind") == "goodput_report"][-1]
    assert rep["config"] == "t_roundtrip"
    assert rep["post_warmup_compiles"] == 0

    pl = _tools("perf_ledger")
    rows, skipped = pl.rows_from_file(out)
    assert skipped == 0
    metrics = {r["metric"] for r in rows}
    assert {"goodput_frac", "input_wait_s"} <= metrics

    mr = _tools("metrics_report")
    buf = io.StringIO()
    mr.report(out, out=buf)
    text = buf.getvalue()
    assert "-- goodput --" in text
    assert "t_roundtrip" in text


def test_report_check_flag_fails_on_broken_snapshot(tmp_path):
    gr = _tools("goodput_report")
    bad = {"kind": "goodput_snapshot", "label": "bad", "wall_s": 1.0,
           "goodput_frac": 0.0, "sum_frac_err": 0.5, "steps": 0,
           "compile_steps": 0, "post_warmup_compiles": 0,
           "input_batches": 0, "starved_steps": 0, "step_records": [],
           "categories": {k: (2.0 if k == "other" else 0.0)
                          for k in goodput.CATEGORIES}}
    p = tmp_path / "bad.jsonl"
    p.write_text(json.dumps(bad) + "\n")
    assert gr.main([str(p), "--check"]) == 1
    assert gr.main([str(p)]) == 0

"""Static program verifier (paddle_tpu/analysis): rule fixtures, clean
passes over the bench model builders and the whole op registry, and the
FLAGS_program_verify pre-compile gate in Executor.run and
ServingEngine.warmup.

Rule catalog: docs/static_analysis.md.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis import (Diagnostic, ProgramVerificationError,
                                 RULES, verify_program)
from paddle_tpu.analysis import verifier as verifier_mod
from paddle_tpu.core.flags import FLAGS
from paddle_tpu.core.registry import REGISTRY
from paddle_tpu.framework import Operator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tools(module):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(module)
    finally:
        sys.path.pop(0)


def _rules(result):
    return {d.rule for d in result.findings}


def _raw_program(var_specs, op_specs):
    """Program from raw Operator appends (append_op would reject some
    fixtures at build time — the verifier must catch them statically)."""
    prog = fluid.Program()
    blk = prog.global_block()
    for name, kw in var_specs:
        blk.create_var(name=name, **kw)
    for op_type, ins, outs, attrs in op_specs:
        blk.ops.append(Operator(blk, op_type, ins, outs, attrs))
    return prog


_F32_23 = dict(shape=[2, 3], dtype="float32")


# ---------------------------------------------------------------------------
# one purpose-built bad program per lint rule
# ---------------------------------------------------------------------------

def test_ptv001_unregistered_op_with_suggestion():
    prog = _raw_program(
        [("a", dict(is_data=True, **_F32_23)), ("b", dict(**_F32_23))],
        [("reluu", {"X": ["a"]}, {"Out": ["b"]}, {})])
    res = verify_program(prog, check_shapes=False)
    hits = [d for d in res.findings if d.rule == "PTV001"]
    assert hits and hits[0].severity == "error"
    assert "relu" in hits[0].message and "did you mean" in hits[0].message
    assert hits[0].where == "reluu:0/0"


def test_ptv002_op_version_mismatch():
    prog = _raw_program(
        [("a", dict(is_data=True, **_F32_23)), ("b", dict(**_F32_23))],
        [("relu", {"X": ["a"]}, {"Out": ["b"]}, {})])
    res = verify_program(prog, op_versions={"relu": 999},
                         check_shapes=False)
    assert "PTV002" in _rules(res)
    assert any(d.severity == "error" for d in res.findings
               if d.rule == "PTV002")


def test_ptv010_undefined_var():
    prog = _raw_program(
        [("b", dict(**_F32_23))],
        [("relu", {"X": ["ghost"]}, {"Out": ["b"]}, {})])
    res = verify_program(prog, check_shapes=False)
    hits = [d for d in res.findings if d.rule == "PTV010"]
    assert hits and hits[0].var == "ghost"


def test_ptv011_use_before_def():
    # "b" is declared but neither data/persistable nor written first
    prog = _raw_program(
        [("b", dict(**_F32_23)), ("c", dict(**_F32_23))],
        [("relu", {"X": ["b"]}, {"Out": ["c"]}, {})])
    res = verify_program(prog, check_shapes=False)
    hits = [d for d in res.findings if d.rule == "PTV011"]
    assert hits and hits[0].var == "b"


def test_ptv012_dead_op():
    prog = _raw_program(
        [("a", dict(is_data=True, **_F32_23)), ("b", dict(**_F32_23)),
         ("dead", dict(**_F32_23))],
        [("relu", {"X": ["a"]}, {"Out": ["b"]}, {}),
         ("tanh", {"X": ["a"]}, {"Out": ["dead"]}, {})])
    res = verify_program(prog, fetch_names=["b"], check_shapes=False)
    hits = [d for d in res.findings if d.rule == "PTV012"]
    assert hits and hits[0].op_type == "tanh" \
        and hits[0].severity == "warn"
    # without a fetch list the reachability lint cannot run
    res2 = verify_program(prog, check_shapes=False)
    assert "PTV012" not in _rules(res2)


def test_ptv013_unused_multi_output():
    prog = _raw_program(
        [("a", dict(is_data=True, **_F32_23)), ("b", dict(**_F32_23)),
         ("mask", dict(**_F32_23))],
        [("dropout", {"X": ["a"]}, {"Out": ["b"], "Mask": ["mask"]},
          {"dropout_prob": 0.5})])
    res = verify_program(prog, fetch_names=["b"], check_shapes=False)
    hits = [d for d in res.findings if d.rule == "PTV013"]
    assert hits and hits[0].var == "mask" and hits[0].severity == "warn"


def test_ptv014_write_after_write():
    prog = _raw_program(
        [("a", dict(is_data=True, **_F32_23)), ("c", dict(**_F32_23))],
        [("relu", {"X": ["a"]}, {"Out": ["c"]}, {}),
         ("tanh", {"X": ["a"]}, {"Out": ["c"]}, {})])
    res = verify_program(prog, fetch_names=["c"], check_shapes=False)
    hits = [d for d in res.findings if d.rule == "PTV014"]
    assert hits and hits[0].var == "c" and hits[0].op_type == "tanh"


def test_ptv014_not_fired_when_read_between():
    prog = _raw_program(
        [("a", dict(is_data=True, **_F32_23)), ("c", dict(**_F32_23)),
         ("d", dict(**_F32_23))],
        [("relu", {"X": ["a"]}, {"Out": ["c"]}, {}),
         ("tanh", {"X": ["c"]}, {"Out": ["d"]}, {}),
         ("relu", {"X": ["a"]}, {"Out": ["c"]}, {})])
    res = verify_program(prog, check_shapes=False)
    assert "PTV014" not in _rules(res)


def test_ptv015_inplace_alias_read_after_update():
    prog = _raw_program(
        [("w", dict(persistable=True, **_F32_23)),
         ("g", dict(is_data=True, **_F32_23)),
         ("lr", dict(is_data=True, shape=[1], dtype="float32")),
         ("r", dict(**_F32_23))],
        [("sgd", {"Param": ["w"], "Grad": ["g"], "LearningRate": ["lr"]},
          {"ParamOut": ["w"]}, {}),
         ("relu", {"X": ["w"]}, {"Out": ["r"]}, {})])
    res = verify_program(prog, check_shapes=False)
    hits = [d for d in res.findings if d.rule == "PTV015"]
    assert hits and hits[0].var == "w" and "sgd" in hits[0].message


def test_ptv020_shape_mismatch():
    prog = _raw_program(
        [("a", dict(is_data=True, **_F32_23)),
         ("c", dict(shape=[9, 9], dtype="float32"))],
        [("relu", {"X": ["a"]}, {"Out": ["c"]}, {})])
    res = verify_program(prog)
    hits = [d for d in res.findings if d.rule == "PTV020"]
    assert hits and hits[0].severity == "error"
    assert "[2, 3]" in hits[0].message and "[9, 9]" in hits[0].message


def test_ptv021_dtype_mismatch():
    prog = _raw_program(
        [("a", dict(is_data=True, **_F32_23)),
         ("c", dict(shape=[2, 3], dtype="int32"))],
        [("relu", {"X": ["a"]}, {"Out": ["c"]}, {})])
    res = verify_program(prog)
    hits = [d for d in res.findings if d.rule == "PTV021"]
    assert hits and "float32" in hits[0].message \
        and "int32" in hits[0].message


def test_ptv022_abstract_eval_failure():
    opdef = REGISTRY.get("relu")
    assert opdef.abstract_eval is None

    def boom(op, in_specs, block):
        raise ValueError("synthetic abstract-eval failure")

    opdef.abstract_eval = boom
    try:
        prog = _raw_program(
            [("a", dict(is_data=True, **_F32_23)),
             ("c", dict(**_F32_23))],
            [("relu", {"X": ["a"]}, {"Out": ["c"]}, {})])
        res = verify_program(prog)
        hits = [d for d in res.findings if d.rule == "PTV022"]
        assert hits and hits[0].severity == "error"
        assert "synthetic abstract-eval failure" in hits[0].message
    finally:
        opdef.abstract_eval = None
        verifier_mod.reset_memo()


def test_ptv030_feed_not_in_program():
    prog = _raw_program(
        [("a", dict(is_data=True, **_F32_23))], [])
    res = verify_program(prog, feed_names=["nope"], check_shapes=False)
    hits = [d for d in res.findings if d.rule == "PTV030"]
    assert hits and hits[0].var == "nope"


def test_ptv031_fetch_unreachable():
    prog = _raw_program(
        [("a", dict(is_data=True, **_F32_23)),
         ("limbo", dict(**_F32_23))], [])
    res = verify_program(prog, fetch_names=["never_declared"],
                         check_shapes=False)
    assert any(d.rule == "PTV031" and d.var == "never_declared"
               for d in res.findings)
    # declared but never produced, not data/persistable, not fed
    res2 = verify_program(prog, fetch_names=["limbo"], check_shapes=False)
    assert any(d.rule == "PTV031" and d.var == "limbo"
               for d in res2.findings)
    # a data var is materialized by the feed path: no finding
    res3 = verify_program(prog, fetch_names=["a"], check_shapes=False)
    assert "PTV031" not in _rules(res3)


def test_ptv040_sub_block_inconsistency():
    prog = _raw_program(
        [("a", dict(is_data=True, **_F32_23)), ("b", dict(**_F32_23))],
        [("while", {"X": ["a"]}, {"Out": ["b"]},
          {"sub_block": 7, "output_vars": ["b"], "carried_vars": ["a"],
           "condition": "cond"})])
    res = verify_program(prog, check_shapes=False)
    hits = [d for d in res.findings if d.rule == "PTV040"]
    assert hits and hits[0].severity == "error" \
        and "sub_block" in hits[0].message


def test_diagnostic_provenance_and_serialization():
    d = Diagnostic(rule="PTV020", message="m", op_type="relu",
                   block=1, op_idx=4, var="x")
    assert d.where == "relu:1/4"
    rec = d.to_dict()
    assert rec["rule"] == "PTV020" and rec["where"] == "relu:1/4" \
        and rec["severity"] == RULES["PTV020"][0]
    assert Diagnostic(rule="PTV030", message="m").where == "program"


# ---------------------------------------------------------------------------
# known-good programs must verify clean
# ---------------------------------------------------------------------------

def test_bench_model_builders_verify_clean():
    """Every tiny bench builder (the models bench.py certifies on CPU)
    produces a program with ZERO error-severity findings."""
    sys.path.insert(0, REPO)
    os.environ.setdefault("BENCH_FLASH", "0")
    import bench
    for model, build in bench._CPU_TINY_BUILDS.items():
        exe, prog, scope, feed, loss, cfg = build()
        res = verify_program(prog, feed_names=list(feed),
                             fetch_names=[loss.name])
        errs = res.errors()
        assert not errs, (
            f"{model}: {len(errs)} error finding(s): "
            + "; ".join(f"{d.rule} {d.where}: {d.message}"
                        for d in errs[:5]))


def test_registry_wide_op_sweep_verifies_clean():
    """One-op programs for every op the committed OP_TEST_MATRIX.json
    certifies as passing: the abstract-evaluation pass must run the
    registered lowering under jax.eval_shape without error findings."""
    from op_specs import SKIPS, SPECS
    import test_op_sweep as sweep

    matrix = json.load(open(os.path.join(REPO, "OP_TEST_MATRIX.json")))
    ops = [op for op, rec in matrix["ops"].items()
           if rec.get("status") == "pass"
           and op in SPECS and op not in SKIPS]
    assert len(ops) > 250, f"matrix shrank unexpectedly: {len(ops)}"
    bad = {}
    for op in ops:
        main, feeds, out_map, _direct, _ = sweep._build_program(
            op, SPECS[op])
        fetch = [nm for names in out_map.values() for nm in names]
        res = verify_program(main, feed_names=list(feeds),
                             fetch_names=fetch)
        if res.errors():
            bad[op] = [f"{d.rule} {d.message[:120]}"
                       for d in res.errors()[:3]]
    assert not bad, f"{len(bad)} op(s) with verifier errors: {bad}"


# ---------------------------------------------------------------------------
# the pre-compile gate
# ---------------------------------------------------------------------------

def _bad_training_program():
    """Feedable program whose compile would crash (undefined input):
    error mode must reject it BEFORE any executable is built."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
    blk = main.global_block()
    blk.create_var(name="y", shape=[-1, 4], dtype="float32")
    blk.ops.append(Operator(blk, "relu", {"X": ["ghost"]},
                            {"Out": ["y"]}))
    return main


def test_executor_gate_error_mode_raises_before_compile():
    verifier_mod.reset_memo()
    fluid.set_flags({"FLAGS_program_verify": "error"})
    try:
        main = _bad_training_program()
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            with pytest.raises(ProgramVerificationError) as ei:
                exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                        fetch_list=["y"])
        msg = str(ei.value)
        assert "PTV010" in msg and "relu:0/" in msg \
            and "FLAGS_program_verify" in msg
        stats = exe.cache_stats()
        assert stats["misses"] == 0 and stats["size"] == 0, stats
    finally:
        fluid.set_flags({"FLAGS_program_verify": "warn"})
        verifier_mod.reset_memo()


def test_executor_gate_warn_mode_warns_once_then_memoizes():
    verifier_mod.reset_memo()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[3], dtype="float32")
    blk = main.global_block()
    blk.create_var(name="c", shape=[-1, 3], dtype="float32")
    # WAW: first write never read -> one PTV014 warn finding, but the
    # program still executes fine
    blk.ops.append(Operator(blk, "relu", {"X": [x.name]}, {"Out": ["c"]}))
    blk.ops.append(Operator(blk, "tanh", {"X": [x.name]}, {"Out": ["c"]}))
    exe = fluid.Executor()
    feed = {"x": np.ones((2, 3), np.float32)}
    with fluid.scope_guard(fluid.Scope()):
        with pytest.warns(UserWarning, match="PTV014"):
            out1 = exe.run(main, feed=feed, fetch_list=["c"])
        # memoized: the second identical run must NOT warn again
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            out2 = exe.run(main, feed=feed, fetch_list=["c"])
        assert not [w for w in rec if "PTV" in str(w.message)], \
            [str(w.message) for w in rec]
    np.testing.assert_allclose(out1[0], np.tanh(feed["x"]), rtol=1e-6)
    np.testing.assert_allclose(out2[0], out1[0])
    verifier_mod.reset_memo()


def test_gate_off_mode_skips_and_bad_flag_value_raises():
    verifier_mod.reset_memo()
    from paddle_tpu.analysis import verify_gate
    main = _bad_training_program()
    fluid.set_flags({"FLAGS_program_verify": "off"})
    try:
        assert verify_gate(main) is None
        fluid.set_flags({"FLAGS_program_verify": "everything"})
        with pytest.raises(ValueError, match="program_verify"):
            verify_gate(main)
    finally:
        fluid.set_flags({"FLAGS_program_verify": "warn"})
        verifier_mod.reset_memo()


def test_serving_warmup_gate_rejects_corrupt_model(tmp_path):
    """A saved model corrupted on disk is rejected by the warmup gate in
    error mode — before a single ladder-cell compile is spent."""
    from paddle_tpu import io
    from paddle_tpu.serving import EngineConfig, ServingEngine

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[6], dtype="float32")
        out = layers.fc(x, size=3, act="relu")
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        mdir = str(tmp_path / "model")
        io.save_inference_model(mdir, ["x"], [out], exe,
                                main_program=main)
    # corrupt: an op reading a var that exists nowhere
    mpath = os.path.join(mdir, "__model__.json")
    with open(mpath) as f:
        meta = json.load(f)
    meta["program"]["blocks"][0]["ops"].insert(
        0, {"type": "relu", "inputs": {"X": ["ghost"]},
            "outputs": {"Out": [out.name]}, "attrs": {}, "id": 999})
    with open(mpath, "w") as f:
        json.dump(meta, f)

    verifier_mod.reset_memo()
    fluid.set_flags({"FLAGS_program_verify": "error"})
    try:
        engine = ServingEngine(EngineConfig(model_dir=mdir,
                                            max_batch_size=2))
        with pytest.raises(ProgramVerificationError, match="PTV010"):
            engine.start()
        stats = engine.cache_stats()
        assert stats["misses"] == 0, stats
    finally:
        fluid.set_flags({"FLAGS_program_verify": "warn"})
        verifier_mod.reset_memo()


# ---------------------------------------------------------------------------
# satellite: feed rank validation + registry suggestions
# ---------------------------------------------------------------------------

def test_feed_rank_mismatch_diagnostic():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[2, 3], dtype="float32")
        y = layers.relu(x)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        with pytest.raises(ValueError) as ei:
            exe.run(main, feed={"x": np.ones((6,), np.float32)},
                    fetch_list=[y.name])
    msg = str(ei.value)
    assert "'x'" in msg and "rank 1" in msg and "rank 3" in msg, msg


def test_registry_get_suggests_and_carries_provenance():
    with pytest.raises(NotImplementedError) as ei:
        REGISTRY.get("reluu")
    assert "did you mean" in str(ei.value) and "'relu'" in str(ei.value)
    with pytest.raises(NotImplementedError) as ei2:
        REGISTRY.get("reluu", where="2/17")
    assert "at block/op 2/17" in str(ei2.value)


# ---------------------------------------------------------------------------
# satellite: CLI + artifact schema + report rendering
# ---------------------------------------------------------------------------

def test_program_lint_self_check_exits_zero():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "program_lint.py"),
         "--self-check"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "self-check ok" in r.stdout


def test_program_lint_cli_end_to_end(tmp_path):
    from paddle_tpu import io

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[6], dtype="float32")
        out = layers.fc(x, size=3, act="relu")
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        good = str(tmp_path / "good")
        io.save_inference_model(good, ["x"], [out], exe,
                                main_program=main)
    bad = str(tmp_path / "bad")
    os.makedirs(bad)
    with open(os.path.join(good, "__model__.json")) as f:
        meta = json.load(f)
    meta["program"]["blocks"][0]["ops"].append(
        {"type": "reluu", "inputs": {"X": ["x"]},
         "outputs": {"Out": ["x"]}, "attrs": {}, "id": 999})
    with open(os.path.join(bad, "__model__.json"), "w") as f:
        json.dump(meta, f)

    log = str(tmp_path / "lint.jsonl")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "program_lint.py"),
         good, bad, "--jsonl", "--out", log],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 1, r.stdout + r.stderr  # bad model -> findings
    recs = [json.loads(l) for l in r.stdout.splitlines() if l.strip()]
    assert len(recs) == 2
    assert recs[0]["ok"] and recs[0]["counts"]["error"] == 0
    assert not recs[1]["ok"] and recs[1]["counts"]["error"] >= 1
    assert any(f["rule"] == "PTV001" for f in recs[1]["findings"])

    # the appended JSONL satisfies the artifact schema ...
    assert _tools("validate_bench_json").validate_file(log) == []
    # ... and metrics_report renders a lint section from it
    import io as pyio
    metrics_report = _tools("metrics_report")
    buf = pyio.StringIO()
    rc = metrics_report.report(log, out=buf)
    text = buf.getvalue()
    assert rc == 0 and "program lint" in text and "PTV001" in text


def test_validate_program_lint_schema():
    validate_program_lint = _tools("validate_bench_json") \
        .validate_program_lint
    good = {"kind": "program_lint", "model": "m", "ok": True,
            "counts": {"error": 0, "warn": 1},
            "findings": [{"rule": "PTV013", "severity": "warn",
                          "where": "dropout:0/3", "message": "x"}]}
    assert validate_program_lint(good) == []
    bad = dict(good, ok=True, counts={"error": 2, "warn": 0})
    errs = validate_program_lint(bad)
    assert errs and any("contradicts" in e for e in errs)
    assert validate_program_lint({"kind": "program_lint"})  # all missing

"""Kernel + precision tests: Pallas flash attention (interpret mode on
CPU), ring attention over the device ring, AMP rewrite, QAT rewrite."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _naive_attn(q, k, v, causal, sm_scale=None):
    d = q.shape[-1]
    t = q.shape[2]
    scale = 1.0 / jnp.sqrt(d) if sm_scale is None else sm_scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


def _qkv(t=64, d=16):
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(2, 2, t, d), jnp.float32)  # noqa
    return mk(), mk(), mk()


def test_flash_attention_matches_naive():
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    q, k, v = _qkv()
    for causal in (False, True):
        out = flash_attention(q, k, v, causal=causal, block_q=32,
                              block_k=32)
        ref = _naive_attn(q, k, v, causal)
        np.testing.assert_allclose(out, ref, atol=2e-5)
        g1 = jax.grad(lambda q_: flash_attention(
            q_, k, v, causal=causal, block_q=32, block_k=32).sum())(q)
        g2 = jax.grad(lambda q_: _naive_attn(q_, k, v, causal).sum())(q)
        np.testing.assert_allclose(g1, g2, atol=2e-5)


def test_ring_attention_matches_naive():
    from jax.sharding import Mesh
    from paddle_tpu.parallel.ring_attention import ring_attention_sharded
    q, k, v = _qkv()
    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("sp",))
    for causal in (False, True):
        out = ring_attention_sharded(q, k, v, mesh, "sp", causal=causal)
        ref = _naive_attn(q, k, v, causal)
        np.testing.assert_allclose(out, ref, atol=2e-5)


def test_amp_bf16_rewrite_and_training():
    from paddle_tpu.models import transformer
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        cfg = transformer.TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
            dropout=0.0, use_flash=False)
        loss, _ = transformer.build_train(cfg, batch=4, seq_len=8,
                                          lr=1e-2, amp=True)
    # rewrite inserted bf16 casts
    types = [op.type for op in main.global_block().ops]
    assert "cast" in types
    exe = fluid.Executor()
    exe.run(startup)
    toks = np.random.RandomState(0).randint(0, 64, (4, 8)).astype(np.int64)
    for _ in range(30):
        lv, = exe.run(main, feed={"tokens": toks, "labels": toks},
                      fetch_list=[loss])
    assert float(np.asarray(lv)) < 1.0


def test_qat_rewrite_trains():
    from paddle_tpu.contrib.slim.quantization import quant_aware
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8], dtype="float32")
        label = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        quant_aware(main, startup)
        loss = layers.mean(layers.square_error_cost(pred, label))
        fluid.optimizer.SGD(0.05).minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert any("fake" in t for t in types), types
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 8).astype(np.float32)
    ys = (xs.sum(1, keepdims=True) > 0).astype(np.float32)
    first = None
    for _ in range(40):
        lv, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        if first is None:
            first = float(np.asarray(lv))
    assert float(np.asarray(lv)) < first


def test_flash_attention_op_in_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        q = layers.data("q", shape=[2, 2, 128, 16], dtype="float32",
                        append_batch_size=False)
        k = layers.data("k", shape=[2, 2, 128, 16], dtype="float32",
                        append_batch_size=False)
        v = layers.data("v", shape=[2, 2, 128, 16], dtype="float32",
                        append_batch_size=False)
        out = layers.flash_attention(q, k, v)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    qv = rng.randn(2, 2, 128, 16).astype(np.float32)
    kv = rng.randn(2, 2, 128, 16).astype(np.float32)
    vv = rng.randn(2, 2, 128, 16).astype(np.float32)
    o, = exe.run(main, feed={"q": qv, "k": kv, "v": vv}, fetch_list=[out])
    ref = _naive_attn(jnp.asarray(qv), jnp.asarray(kv), jnp.asarray(vv),
                      False)
    np.testing.assert_allclose(o, ref, atol=2e-5)


def test_ring_attention_gradients_match_naive():
    """Blockwise ring backward (custom vjp): dq/dk/dv must match the
    naive attention gradients across the 8-way sequence ring, causal
    and bidirectional."""
    from jax.sharding import Mesh
    from paddle_tpu.parallel.ring_attention import ring_attention_sharded
    q, k, v = _qkv()
    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("sp",))
    for causal in (False, True):
        def ring_loss(q_, k_, v_):
            out = ring_attention_sharded(q_, k_, v_, mesh, "sp",
                                         causal=causal)
            return (out.astype(jnp.float32) ** 2).sum()

        def ref_loss(q_, k_, v_):
            return (_naive_attn(q_, k_, v_, causal)
                    .astype(jnp.float32) ** 2).sum()

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for gr, gn, name in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(gr, gn, atol=3e-4,
                                       err_msg=f"d{name} causal={causal}")


def test_flash_attention_kernel_path_t256():
    """Exercises the real tiled kernel path (t >= 128: grid-streamed
    k/v + VMEM scratch + causal index-map clamping), not the small-t
    exact fallback."""
    from paddle_tpu.ops.pallas.flash_attention import (flash_attention,
                                                       reference_attention)
    k0 = jax.random.PRNGKey(0)
    q = jax.random.normal(k0, (2, 256, 32), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (2, 256, 32), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (2, 256, 32), jnp.float32)
    for causal in (False, True):
        o = flash_attention(q, k, v, causal=causal, block_q=128,
                            block_k=128)
        r = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(o, r, atol=3e-5)
        gk = jax.grad(lambda k_: flash_attention(
            q, k_, v, causal=causal).sum())(k)
        gkr = jax.grad(lambda k_: reference_attention(
            q, k_, v, causal=causal).sum())(k)
        np.testing.assert_allclose(gk, gkr, atol=3e-4)


def test_ulysses_blockwise_full_attn():
    """The O(T·block)-memory blockwise path (used for long sequences so
    Ulysses never materializes the T^2 score matrix) matches dense
    attention, including the ragged final block (pad path) and its
    gradients."""
    from paddle_tpu.parallel.ulysses import _blockwise_full_attn
    rng = np.random.RandomState(5)
    mk = lambda t: jnp.asarray(rng.randn(1, 2, t, 8), jnp.float32)  # noqa
    for t, blk in ((32, 8), (20, 8)):  # exact split + ragged tail
        q, k, v = mk(t), mk(t), mk(t)
        for causal in (False, True):
            o = _blockwise_full_attn(q, k, v, 0.35, causal, block_k=blk)
            ref = _naive_attn(q, k, v, causal, sm_scale=0.35)
            np.testing.assert_allclose(o, ref, atol=2e-5)
            gb = jax.grad(lambda q_: (_blockwise_full_attn(
                q_, k, v, 0.35, causal, block_k=blk) ** 2).sum())(q)
            gr = jax.grad(lambda q_: (_naive_attn(
                q_, k, v, causal, sm_scale=0.35) ** 2).sum())(q)
            np.testing.assert_allclose(gb, gr, atol=3e-4)


def test_ulysses_attention_matches_naive():
    """All-to-all (Ulysses) sequence parallelism: output and gradients
    must match naive attention across the 8-way mesh, causal and not."""
    from jax.sharding import Mesh
    from paddle_tpu.parallel.ulysses import ulysses_attention_sharded
    rng = np.random.RandomState(3)
    mk = lambda: jnp.asarray(rng.randn(2, 8, 32, 8), jnp.float32)  # noqa
    q, k, v = mk(), mk(), mk()  # h=8 divides sp=8
    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("sp",))
    for causal in (False, True):
        out = ulysses_attention_sharded(q, k, v, mesh, "sp",
                                        causal=causal)
        ref = _naive_attn(q, k, v, causal)
        np.testing.assert_allclose(out, ref, atol=2e-5)

        def u_loss(q_, k_, v_):
            o = ulysses_attention_sharded(q_, k_, v_, mesh, "sp",
                                          causal=causal)
            return (o.astype(jnp.float32) ** 2).sum()

        def n_loss(q_, k_, v_):
            return (_naive_attn(q_, k_, v_, causal)
                    .astype(jnp.float32) ** 2).sum()

        gu = jax.grad(u_loss, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(n_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gu, gn, "qkv"):
            np.testing.assert_allclose(a, b, atol=3e-4,
                                       err_msg=f"d{name} causal={causal}")

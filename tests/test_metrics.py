"""Host-metric tests (reference: python/paddle/fluid/metrics.py + the
unittests test_metrics.py family), focused on the DetectionMAP
evaluator's streamed accumulation."""
import numpy as np

import jax.numpy as jnp

from paddle_tpu import metrics
from paddle_tpu.core.registry import REGISTRY


def _op_map(det, lab, **attrs):
    class _Ctx:
        is_test = True
        mesh = None
        block = None
        attrs = {}
        rng = None

    out = REGISTRY.get("detection_map").lower(
        _Ctx(), {"DetectRes": [jnp.asarray(det)],
                 "Label": [jnp.asarray(lab)]},
        {"overlap_threshold": 0.5, **attrs})
    return float(np.asarray(out["MAP"][0])[0])


DET1 = np.array([[1.0, 0.90, 0.00, 0.00, 0.40, 0.38],
                 [1.0, 0.80, 0.02, 0.02, 0.42, 0.40],
                 [1.0, 0.70, 0.50, 0.55, 0.90, 0.95],
                 [2.0, 0.85, 0.21, 0.20, 0.70, 0.71]], np.float32)
GT_LABEL1 = np.array([[1], [1], [2]], np.int64)
GT_BOX1 = np.array([[0.00, 0.00, 0.40, 0.40],
                    [0.50, 0.50, 0.90, 0.90],
                    [0.20, 0.20, 0.70, 0.70]], np.float32)


def test_detection_map_metric_matches_op_single_image():
    """One update() == the detection_map op on the same data (the op is
    single-image; the metric's value-add is the cross-image stream)."""
    for ap in ("integral", "11point"):
        m = metrics.DetectionMAP(ap_version=ap)
        m.update(DET1, GT_LABEL1, GT_BOX1)
        lab = np.concatenate(
            [GT_LABEL1.astype(np.float32),
             np.zeros((3, 1), np.float32), GT_BOX1], axis=1)
        assert abs(m.eval() - _op_map(DET1, lab, ap_type=ap)) < 1e-6


def test_detection_map_metric_streams_across_images():
    """A second image whose detection is a duplicate-style miss must
    lower the accumulated mAP below the single-image value."""
    m = metrics.DetectionMAP()
    m.update(DET1, GT_LABEL1, GT_BOX1)
    one = m.eval()
    # image 2: one GT of class 1, detection misses it (low IoU)
    m.update(np.array([[1.0, 0.95, 0.6, 0.6, 0.9, 0.9]], np.float32),
             np.array([[1]], np.int64),
             np.array([[0.0, 0.0, 0.3, 0.3]], np.float32))
    two = m.eval()
    assert two < one, (one, two)
    m.reset()
    assert m.eval() == 0.0


def test_detection_map_metric_difficult_excluded():
    m = metrics.DetectionMAP(evaluate_difficult=False)
    m.update(DET1[:1], np.array([[1], [1]], np.int64),
             np.array([[0.0, 0.0, 0.4, 0.4],
                       [0.5, 0.5, 0.9, 0.9]], np.float32),
             gt_difficult=np.array([[0], [1]], np.int64))
    # the difficult GT does not count toward npos: the single perfect
    # detection yields AP 1.0
    assert abs(m.eval() - 1.0) < 1e-6


def test_detection_map_metric_background_ignored():
    m = metrics.DetectionMAP(background_label=1)
    m.update(DET1, GT_LABEL1, GT_BOX1)
    # class 1 is background now: only class 2 (perfect match) remains
    assert abs(m.eval() - 1.0) < 1e-6

"""Serving-subsystem tests: bucket ladder, dynamic batcher under real
concurrency, shape-bucketed warmup (zero post-warmup compiles), HTTP
front end smoke, and the loadgen JSONL schema.

The test model (x[b, t, 6] -> reduce_sum over t -> fc -> softmax) is
seq-pad INVARIANT (appended zero timesteps contribute nothing to the
sum), so engine outputs for bucket-padded batches are directly
comparable to unbatched, unpadded reference outputs.
"""
import contextlib
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor
from paddle_tpu.serving import (BucketLadder, DeadlineExceededError,
                                DynamicBatcher, EngineClosedError,
                                EngineConfig, QueueFullError,
                                ServingEngine, serve)

FEAT = 6


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("serving_model"))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, -1, FEAT], dtype="float32",
                        append_batch_size=False)
        s = layers.reduce_sum(x, dim=1)
        h = layers.fc(s, size=16, act="relu")
        pred = layers.fc(h, size=4, act="softmax")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [pred], exe,
                                      main_program=main)
    return d


def _engine(model_dir, **kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("seq_buckets", (4, 8))
    kw.setdefault("max_wait_us", 1000)
    kw.setdefault("queue_capacity", 64)
    kw.setdefault("default_timeout_ms", 10000)
    return ServingEngine(EngineConfig(model_dir, **kw))


@contextlib.contextmanager
def _running(engine):
    engine.start()
    try:
        yield engine
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# BucketLadder
# ---------------------------------------------------------------------------

def test_bucket_ladder_quantization():
    lad = BucketLadder((1, 2, 4), seq_buckets=(8, 16), seq_axis=1)
    assert lad.bucket_batch(1) == 1 and lad.bucket_batch(3) == 4
    assert lad.bucket_seq(5) == 8 and lad.bucket_seq(16) == 16
    with pytest.raises(ValueError):
        lad.bucket_batch(5)
    with pytest.raises(ValueError):
        lad.bucket_seq(17)
    arr = np.ones((2, 5, 3), np.float32)
    padded = lad.pad_seq(arr)
    assert padded.shape == (2, 8, 3)
    assert np.all(padded[:, 5:] == 0) and np.all(padded[:, :5] == 1)
    b = lad.pad_batch(padded, 4)
    assert b.shape == (4, 8, 3) and np.all(b[2:] == 0)


def test_ladder_shapes_match_warmup_grid(model_dir):
    eng = _engine(model_dir, warmup=False)
    assert eng.warmup_shapes() == [(1, 4), (1, 8), (2, 4), (2, 8),
                                   (4, 4), (4, 8)]


# ---------------------------------------------------------------------------
# DynamicBatcher semantics (no engine: drive next_batch by hand)
# ---------------------------------------------------------------------------

def _batcher(**kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_wait_us", 500)
    kw.setdefault("queue_capacity", 8)
    return DynamicBatcher(
        BucketLadder((1, 2, 4), seq_buckets=(4, 8)), **kw)


def test_batcher_coalesces_same_bucket():
    b = _batcher()
    r1 = b.submit({"x": np.ones((1, 3, FEAT), np.float32)})
    r2 = b.submit({"x": np.ones((1, 4, FEAT), np.float32)})
    batch = b.next_batch(timeout=1.0)
    assert batch is not None and len(batch.requests) == 2
    feed, bucket, waste = batch.build_feed(b.ladder)
    assert feed["x"].shape == (2, 4, FEAT) and bucket == 2
    assert waste == 0.0  # both requests seq-padded to the same 4-bucket
    batch.scatter([np.arange(2 * 5).reshape(2, 5)])
    assert r1.result(1.0)[0].shape == (1, 5)
    assert np.array_equal(r2.result(1.0)[0],
                          np.arange(5, 10).reshape(1, 5))


def test_batcher_separates_incompatible_buckets():
    b = _batcher()
    b.submit({"x": np.ones((1, 3, FEAT), np.float32)})   # 4-bucket
    b.submit({"x": np.ones((1, 7, FEAT), np.float32)})   # 8-bucket
    got = {b.next_batch(1.0).requests[0].feed["x"].shape[1]
           for _ in range(2)}
    assert got == {4, 8}


def test_batcher_flushes_at_max_batch_size_before_window():
    b = _batcher(max_wait_us=10_000_000)  # window would be 10s
    for _ in range(4):
        b.submit({"x": np.ones((1, 3, FEAT), np.float32)})
    t0 = time.perf_counter()
    batch = b.next_batch(timeout=5.0)
    assert batch is not None and batch.rows == 4
    assert time.perf_counter() - t0 < 1.0  # size-triggered, not window


def test_batcher_deadline_timeout():
    b = _batcher(max_wait_us=10_000_000, max_batch_size=4)
    resp = b.submit({"x": np.ones((1, 3, FEAT), np.float32)},
                    timeout_ms=50)
    # the consumer is what expires deadlines; the batch never matures
    assert b.next_batch(timeout=1.0) is None
    with pytest.raises(DeadlineExceededError):
        resp.result(1.0)
    assert b.pending_rows() == 0


def test_batcher_backpressure_rejection():
    b = _batcher(queue_capacity=2, max_wait_us=10_000_000)
    b.submit({"x": np.ones((1, 3, FEAT), np.float32)})
    b.submit({"x": np.ones((1, 3, FEAT), np.float32)})
    with pytest.raises(QueueFullError):
        b.submit({"x": np.ones((1, 3, FEAT), np.float32)})
    # capacity is rows, not requests: a 2-row request can't fit either
    with pytest.raises(QueueFullError):
        b.submit({"x": np.ones((2, 3, FEAT), np.float32)})


def test_batcher_submit_validation():
    b = _batcher()
    with pytest.raises(ValueError):
        b.submit({})
    with pytest.raises(ValueError):
        b.submit({"x": np.float32(1.0)})          # no batch dim
    with pytest.raises(ValueError):
        b.submit({"x": np.ones((8, 3, FEAT))})    # > max_batch_size
    with pytest.raises(ValueError):
        b.submit({"x": np.ones((1, 99, FEAT))})   # over the seq ladder


def test_batcher_close_without_drain_fails_pending():
    b = _batcher(max_wait_us=10_000_000)
    resp = b.submit({"x": np.ones((1, 3, FEAT), np.float32)})
    b.close(drain=False)
    with pytest.raises(EngineClosedError):
        resp.result(1.0)
    with pytest.raises(EngineClosedError):
        b.submit({"x": np.ones((1, 3, FEAT), np.float32)})
    assert b.next_batch(timeout=0.1) is None


def test_batcher_close_with_drain_flushes_immature_group():
    b = _batcher(max_wait_us=10_000_000)
    resp = b.submit({"x": np.ones((1, 3, FEAT), np.float32)})
    b.close(drain=True)
    batch = b.next_batch(timeout=1.0)   # immature group force-flushed
    assert batch is not None and len(batch.requests) == 1
    batch.scatter([np.zeros((1, 4))])
    assert resp.result(1.0)[0].shape == (1, 4)


# ---------------------------------------------------------------------------
# Engine: concurrency correctness, warmup coverage, drain
# ---------------------------------------------------------------------------

def test_engine_concurrent_mixed_shapes_match_reference(model_dir):
    rng = np.random.RandomState(7)
    requests = [rng.randn(int(rng.randint(1, 3)),
                          int(rng.randint(1, 9)),
                          FEAT).astype(np.float32) for _ in range(30)]
    # references computed serially on an independent predictor (the
    # executor's donated-state step is not reentrant)
    ref = create_paddle_predictor(AnalysisConfig(model_dir))
    want = [ref.run_dict({"x": xb})[0] for xb in requests]

    with _running(_engine(model_dir)) as eng:
        got = [None] * len(requests)
        errors = []

        def client(lo, hi):
            try:
                for i in range(lo, hi):
                    got[i] = eng.predict({"x": requests[i]})[0]
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i, i + 5))
                   for i in range(0, len(requests), 5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors, errors
    for i, (g, w) in enumerate(zip(got, want)):
        assert g.shape == w.shape, i
        np.testing.assert_allclose(g, np.asarray(w), rtol=1e-5,
                                   atol=1e-6, err_msg=f"request {i}")


def test_engine_warmup_covers_ladder_zero_post_warmup_compiles(model_dir):
    eng = _engine(model_dir)
    with _running(eng):
        stats = eng.cache_stats()
        assert stats["misses"] == len(eng.warmup_shapes())
        rng = np.random.RandomState(3)
        with ThreadsDriving(eng, rng, n_threads=4, per_thread=8):
            pass
        after = eng.cache_stats()
    assert after["misses"] == stats["misses"], \
        "post-warmup traffic inside the ladder must not compile"
    assert after["hits"] > stats["hits"]


class ThreadsDriving:
    """Context manager: N threads each firing mixed-ladder requests."""

    def __init__(self, engine, rng, n_threads, per_thread):
        self.engine = engine
        self.seeds = [int(rng.randint(1 << 30))
                      for _ in range(n_threads)]
        self.per_thread = per_thread
        self.errors = []

    def __enter__(self):
        def run(seed):
            r = np.random.RandomState(seed)
            try:
                for _ in range(self.per_thread):
                    xb = r.randn(int(r.randint(1, 3)),
                                 int(r.randint(1, 9)),
                                 FEAT).astype(np.float32)
                    self.engine.predict({"x": xb})
            except Exception as e:  # noqa: BLE001
                self.errors.append(e)

        self.threads = [threading.Thread(target=run, args=(s,))
                        for s in self.seeds]
        for t in self.threads:
            t.start()
        return self

    def __exit__(self, *exc):
        for t in self.threads:
            t.join()
        assert not self.errors, self.errors
        return False


def test_engine_without_warmup_compiles_under_traffic(model_dir):
    """The control arm of the acceptance criterion: warmup off, the
    same ladder traffic does trigger executor compiles."""
    eng = _engine(model_dir, warmup=False)
    with _running(eng):
        assert eng.cache_stats()["misses"] == 0
        eng.predict({"x": np.ones((1, 3, FEAT), np.float32)})
        assert eng.cache_stats()["misses"] >= 1


def test_engine_drain_completes_queued_requests(model_dir):
    eng = _engine(model_dir, max_wait_us=10_000_000)  # 10s window:
    # requests sit queued until drain force-flushes them
    with _running(eng):
        pass  # warmed
    eng2 = _engine(model_dir, max_wait_us=10_000_000, warmup=False)
    eng2.predictor = eng.predictor.clone()  # reuse warmed cache
    eng2.start()
    resps = [eng2.submit({"x": np.ones((1, 3, FEAT), np.float32)})
             for _ in range(3)]
    eng2.stop(drain=True)
    for r in resps:
        out = r.result(5.0)
        assert out[0].shape == (1, 4)


def test_engine_rejects_oversized_and_unknown(model_dir):
    eng = _engine(model_dir, warmup=False)
    with _running(eng):
        with pytest.raises(ValueError):
            eng.predict({"x": np.ones((1, 99, FEAT), np.float32)})
        with pytest.raises(ValueError):
            eng.predict({"x": np.ones((9, 3, FEAT), np.float32)})


def test_engine_serving_stats_recorded(model_dir):
    from paddle_tpu import monitor
    prev = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": True})
    monitor.reset_stats()
    try:
        with _running(_engine(model_dir)) as eng:
            for _ in range(3):
                eng.predict({"x": np.ones((1, 3, FEAT), np.float32)})
            snap = monitor.get_stats_snapshot()
        c, h = snap["counters"], snap["histograms"]
        assert c["serving.requests"] == 3
        assert c["serving.batches"] >= 1
        assert c["serving.warmup_shapes"] == 6
        assert h["serving.batch_size"]["count"] == c["serving.batches"]
        assert h["serving.e2e_ms"]["count"] == 3
        assert h["serving.queue_wait_ms"]["count"] == 3
        assert h["serving.pad_waste_frac"]["count"] >= 1
        assert snap["gauges"]["serving.queue_depth"] == 0
    finally:
        monitor.reset_stats()
        fluid.set_flags({"FLAGS_enable_monitor": prev})


# ---------------------------------------------------------------------------
# Throughput: batched engine vs serial single-request dispatch
# ---------------------------------------------------------------------------

def test_batched_beats_serial_dispatch(model_dir):
    """CPU smoke bench: 8 closed-loop clients through the warmed batcher
    vs the same mixed-shape requests serially through a bare (cloned, so
    cache-sharing) predictor. The serial path has no bucket ladder, so
    every novel raw (1, seq) shape is a fresh XLA specialization — the
    recompile pathology the serving layer exists to prevent. The warmed
    engine must win outright (~10x+ in practice)."""
    rng = np.random.RandomState(11)
    reqs = [rng.randn(1, int(rng.randint(1, 9)),
                      FEAT).astype(np.float32) for _ in range(96)]
    eng = _engine(model_dir, max_batch_size=8,
                  queue_capacity=256)
    with _running(eng):
        ref = eng.predictor.clone()
        t0 = time.perf_counter()
        for xb in reqs:
            ref.run_dict({"x": xb})
        serial_s = time.perf_counter() - t0
        # the clone shares the engine's compile cache, so the serial
        # sweep must not have perturbed the engine's warmed ladder —
        # but it does add raw-shape compiles of its own
        assert eng.cache_stats()["misses"] > len(eng.warmup_shapes())

        done = threading.Barrier(9)
        t_batched = [None]

        def client(idx):
            for i in range(idx, len(reqs), 8):
                eng.predict({"x": reqs[i]})
            done.wait()

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        done.wait()
        t_batched[0] = time.perf_counter() - t0
        for t in threads:
            t.join()
    assert t_batched[0] < serial_s / 1.2, \
        f"batched {t_batched[0]:.3f}s not faster than serial " \
        f"{serial_s:.3f}s"


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------

def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_http_smoke(model_dir):
    """Tier-1 serving smoke: start the engine on the tiny CPU model,
    POST one request, assert 200 + /healthz + /metrics scrape."""
    from paddle_tpu import monitor
    prev = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": True})
    monitor.reset_stats()
    eng = _engine(model_dir)
    srv = serve(eng, port=0)   # ephemeral port; also starts the engine
    try:
        url = srv.url
        code, _ = _get(url + "/healthz")
        assert code == 200

        xb = np.random.RandomState(0).randn(1, 5, FEAT) \
            .astype(np.float32)
        ref = create_paddle_predictor(AnalysisConfig(model_dir))
        want, = ref.run_dict({"x": xb})
        code, body = _post(url + "/v1/predict",
                           {"inputs": {"x": xb.tolist()}})
        assert code == 200, body
        name = eng.output_names()[0]
        assert body["shapes"][name] == [1, 4]
        np.testing.assert_allclose(np.asarray(body["outputs"][name]),
                                   np.asarray(want), rtol=1e-4,
                                   atol=1e-5)

        code, raw = _get(url + "/metrics")
        assert code == 200
        text = raw.decode()
        assert "paddle_tpu_serving_requests" in text
        assert "paddle_tpu_serving_batch_size_bucket" in text

        code, body = _post(url + "/v1/predict", {"inputs": {}})
        assert code == 400
        code, _ = _get(url + "/nope")
        assert code == 404
    finally:
        srv.close()
        eng.stop()
        monitor.reset_stats()
        fluid.set_flags({"FLAGS_enable_monitor": prev})
    # after stop the engine reports unready (route returns 503 — the
    # server is closed here, so assert on the engine itself)
    assert not eng.ready


# ---------------------------------------------------------------------------
# Loadgen: schema + report rendering
# ---------------------------------------------------------------------------

def _load_tool(name):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def test_loadgen_jsonl_schema_and_validator(model_dir, tmp_path, capsys):
    loadgen = _load_tool("serving_loadgen")
    v = _load_tool("validate_bench_json")
    out = str(tmp_path / "loadgen.jsonl")
    rc = loadgen.main(["--model-dir", model_dir, "--requests", "24",
                       "--concurrency", "4", "--seq-buckets", "4,8",
                       "--max-batch-size", "4", "--compare-serial",
                       "--check-compiles", "--out", out])
    capsys.readouterr()
    assert rc == 0, "post-warmup compiles detected by --check-compiles"
    assert v.validate_file(out) == []
    recs = [json.loads(l) for l in open(out) if l.strip()]
    assert [r["mode"] for r in recs] == ["closed", "serial_baseline"]
    assert recs[0]["cache"]["post_warmup_compiles"] == 0
    assert recs[1]["cache"]["serial_compiles"] > 0
    assert recs[0]["throughput_rps"] > recs[1]["throughput_rps"]
    assert recs[0]["requests"] == 24 and recs[0]["errors"] == 0
    for q in ("p50", "p95", "p99"):
        assert isinstance(recs[0]["latency_ms"][q], float)

    # schema violations must be caught
    bad = dict(recs[0])
    bad["latency_ms"] = {"p50": 1.0}
    errs = v.validate_loadgen(bad)
    assert any("p95" in e for e in errs)
    bad2 = dict(recs[0], throughput_rps="fast")
    assert any("throughput_rps" in e for e in v.validate_loadgen(bad2))


def test_metrics_report_renders_serving_section(model_dir, tmp_path):
    import io as _io
    metrics_report = _load_tool("metrics_report")
    from paddle_tpu import monitor
    prev = fluid.FLAGS.enable_monitor
    fluid.set_flags({"FLAGS_enable_monitor": True})
    monitor.reset_stats()
    log = str(tmp_path / "serve.jsonl")
    try:
        with _running(_engine(model_dir)) as eng:
            for _ in range(4):
                eng.predict({"x": np.ones((1, 3, FEAT), np.float32)})
            monitor.snapshot_to_jsonl(log)
    finally:
        monitor.reset_stats()
        fluid.set_flags({"FLAGS_enable_monitor": prev})
    with open(log, "a") as f:
        f.write(json.dumps({
            "kind": "serving_loadgen", "mode": "closed", "requests": 4,
            "errors": 0, "duration_s": 0.1, "throughput_rps": 40.0,
            "latency_ms": {"mean": 2.0, "p50": 2.0, "p95": 3.0,
                           "p99": 3.0, "max": 3.0},
            "config": {}, "cache": {"post_warmup_compiles": 0}}) + "\n")
    buf = _io.StringIO()
    rc = metrics_report.report(log, out=buf)
    out = buf.getvalue()
    assert rc == 0
    assert "-- serving --" in out
    assert "requests" in out and "batch size" in out
    assert "loadgen[closed]" in out and "post-warmup compiles 0" in out

"""STE-contract tests for the fake-quantization ops.

The fake-quant ops register a straight-through-estimator gradient
(quant_ops._ste_grad): the cotangent passes through UNCHANGED, by
design NOT the numeric derivative of the staircase (which is 0 almost
everywhere) and NOT scaled by the dequant factor s/bin_cnt. Reference:
fake_quantize_op.cc registers FakeQuantGradOp as dX = dOut (QAT master
weights are updated with the gradient taken at the quantized weight).

These tests pin that contract explicitly per op: with loss = mean(Out),
the analytic dX through the Program-IR backward must equal exactly
ones/size — a staircase derivative would be ~0 and a dequant-scaled
pass-through would be off by s/bin_cnt.
"""
from __future__ import annotations

import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.backward import append_backward
from paddle_tpu.core.registry import REGISTRY
from paddle_tpu.framework import grad_var_name
from paddle_tpu.ops import quant_ops

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from op_specs import SPECS  # noqa: E402
from test_op_sweep import _build_program, _float_out_names  # noqa: E402

STE_OPS = sorted(
    t for t in REGISTRY.types()
    if REGISTRY.get(t).manual_grad is quant_ops._ste_grad)


def test_ste_registry_coverage():
    """Every fake-quant/dequant op carries the STE manual grad."""
    assert set(STE_OPS) >= {
        "fake_quantize_abs_max", "fake_channel_wise_quantize_abs_max",
        "fake_quantize_moving_average_abs_max",
        "fake_quantize_dequantize_moving_average_abs_max",
        "fake_quantize_range_abs_max", "fake_dequantize_max_abs",
        "fake_channel_wise_dequantize_max_abs"}, STE_OPS


@pytest.mark.parametrize("op", STE_OPS)
def test_ste_gradient_is_identity(op):
    spec = dict(SPECS[op])
    spec["grad"] = ("X",)
    main, feeds, out_map, direct, grad_names = _build_program(
        op, spec, grad_slots=("X",))
    opdef = REGISTRY.get(op)
    blk = main.global_block()
    with fluid.program_guard(main):
        means = []
        for slot, nm in _float_out_names(out_map, direct):
            if slot in opdef.nondiff_outputs or slot != "Out":
                continue
            m = blk.create_var(name=f"{nm}__mean", stop_gradient=False)
            blk.append_op("mean", inputs={"X": [nm]},
                          outputs={"Out": [m.name]})
            means.append(m.name)
        assert means, f"{op}: no differentiable Out"
        loss = blk.create_var(name="loss__", stop_gradient=False)
        blk.append_op("sum", inputs={"X": means},
                      outputs={"Out": [loss.name]})
        append_backward(blk.var("loss__"))

    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        analytic, = exe.run(main, feed=feeds,
                            fetch_list=[grad_var_name(grad_names[0])])
    x = feeds[grad_names[0]]
    want = np.full(x.shape, 1.0 / x.size, np.float32)
    # exact: the STE is dX = dOut with no staircase zeros and no
    # s/bin_cnt scaling
    np.testing.assert_allclose(np.asarray(analytic), want, rtol=1e-6,
                               err_msg=f"{op}: STE pass-through violated")

"""Targeted tests for the straggler op batch (straggler_ops.py):
deformable conv equals plain conv at zero offsets, BoxPS pull/push
round-trip, host reader infeed, conditional_block_infer delegation."""
import numpy as np

import jax.numpy as jnp

import paddle_tpu  # noqa: F401 — registers ops
from paddle_tpu.core.registry import REGISTRY
from paddle_tpu.ops import straggler_ops

from test_parity_ops import run

rng = np.random.RandomState(42)


def test_deformable_conv_zero_offset_equals_conv():
    """Zero offsets + unit mask degrade to a standard convolution."""
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32)
    attrs = {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
             "groups": 1, "deformable_groups": 1}
    off = np.zeros((1, 18, 5, 5), np.float32)
    mask = np.ones((1, 9, 5, 5), np.float32)
    got = np.asarray(run("deformable_conv",
                         {"Input": [x], "Filter": [w], "Offset": [off],
                          "Mask": [mask]}, attrs)["Output"][0])
    want = np.asarray(run("conv2d", {"Input": [x], "Filter": [w]},
                          attrs)["Output"][0])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_deformable_conv_mask_scales_contribution():
    x = np.ones((1, 1, 3, 3), np.float32)
    w = np.ones((1, 1, 1, 1), np.float32)
    attrs = {"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
             "groups": 1, "deformable_groups": 1}
    off = np.zeros((1, 2, 3, 3), np.float32)
    half = np.full((1, 1, 3, 3), 0.5, np.float32)
    got = np.asarray(run("deformable_conv",
                         {"Input": [x], "Filter": [w], "Offset": [off],
                          "Mask": [half]}, attrs)["Output"][0])
    np.testing.assert_allclose(got, 0.5, rtol=1e-6)


def test_pull_push_box_sparse_roundtrip():
    straggler_ops.box_sparse_init(table_id=3, vocab=10, dim=4, seed=1)
    ids = np.array([[2], [7]], np.int64)
    out1 = np.asarray(run("pull_box_sparse", {"Ids": [ids]},
                          {"size": 4, "table_id": 3})["Out"][0])
    assert out1.shape == (2, 1, 4)
    # push a gradient for id 2 and re-pull: the row must move
    g = np.ones((2, 1, 4), np.float32)
    run("push_box_sparse", {"Ids": [ids], "Grad": [g]},
        {"table_id": 3, "learning_rate": 0.5})
    out2 = np.asarray(run("pull_box_sparse", {"Ids": [ids]},
                          {"size": 4, "table_id": 3})["Out"][0])
    np.testing.assert_allclose(out2, out1 - 0.5, rtol=1e-5, atol=1e-6)


def test_read_op_pops_host_batches():
    batches = [(np.full((2, 3), i, np.float32),
                np.full((2, 1), i, np.int64)) for i in range(3)]
    it = iter(batches)
    straggler_ops.register_reader(11, lambda: next(it))
    handle = run("create_custom_reader", {}, {"reader_id": 11})["Out"][0]
    outs = run("read", {"Reader": [handle]},
               {"shapes": [[2, 3], [2, 1]],
                "dtypes": ["float32", "int64"]})["Out"]
    assert float(np.asarray(outs[0])[0, 0]) == 0.0
    outs = run("read", {"Reader": [handle]},
               {"shapes": [[2, 3], [2, 1]],
                "dtypes": ["float32", "int64"]})["Out"]
    assert float(np.asarray(outs[0])[0, 0]) == 1.0


def test_inception_fusion_channel_contract():
    """Output channels follow the reference InferShape formula
    (fusion_conv_inception_op.cc:38-42)."""
    x = rng.randn(1, 4, 5, 5).astype(np.float32)
    f0 = rng.randn(2, 4, 1, 1).astype(np.float32)
    f1 = rng.randn(7, 4, 1, 1).astype(np.float32)
    f2 = rng.randn(5, 2, 3, 3).astype(np.float32)
    f3 = rng.randn(4, 3, 3, 3).astype(np.float32)
    out = run("conv2d_inception_fusion",
              {"Input": [x], "Filter": [f0, f1, f2, f3]},
              {"activation": "relu"})["Output"][0]
    want_c = 2 + (7 - 2 * 2) + (5 - 3) + 4
    assert out.shape == (1, want_c, 5, 5)


def test_fl_listen_and_serv_routes_like_ps():
    assert REGISTRY.has("fl_listen_and_serv")
    # the executor routes fl programs to the PS runtime before lowering;
    # direct lowering must refuse loudly
    import pytest
    with pytest.raises(RuntimeError, match="server loop"):
        run("fl_listen_and_serv", {}, {})

"""Train DeepLabv3+ on a synthetic segmentation task — the PaddleCV
deeplabv3+ workload shape (BASELINE config 5: dilated convs + large
activations) on paddle_tpu.

    python examples/train_deeplab.py [--cpu] [--steps N] [--size S]

One XLA computation per step: dilated ResNet backbone (output stride
16), ASPP, the v3+ decoder, per-pixel CE, momentum SGD.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (default: attached TPU)")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--size", type=int, default=65,
                    help="square crop size (513 = Cityscapes scale)")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--classes", type=int, default=5)
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.models import deeplab

    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        loss, feeds = deeplab.build_train(
            img_hw=args.size, batch=args.batch, n_classes=args.classes,
            lr=5e-3)
        exe = fluid.Executor()
        exe.run(startup)

        # synthetic task: segment by which half of the image is brighter
        rng = np.random.RandomState(0)
        img = rng.randn(args.batch, 3, args.size, args.size) \
            .astype(np.float32)
        lab = np.zeros((args.batch, args.size, args.size), np.int64)
        lab[:, :, args.size // 2:] = 1
        img[:, :, :, args.size // 2:] += 1.5  # brightness cue

        for step in range(args.steps):
            lv, = exe.run(main_prog, feed={"image": img, "label": lab},
                          fetch_list=[loss])
            if step % 3 == 0 or step == args.steps - 1:
                print(f"step {step}: loss {float(np.asarray(lv)):.4f}",
                      flush=True)


if __name__ == "__main__":
    main()

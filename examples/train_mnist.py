"""Train a LeNet digit classifier — the book/02.recognize_digits
tutorial on paddle_tpu (reference:
python/paddle/fluid/tests/book/test_recognize_digits.py).

    python examples/train_mnist.py [--cpu] [--epochs N]

The whole step (forward + backward + Adam) compiles to ONE XLA
computation; the DataLoader stages batches through a prefetch queue.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (default: attached TPU)")
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch", type=int, default=128)
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.datasets import mnist
    from paddle_tpu.models import lenet

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        img = layers.data("img", shape=[1, 28, 28], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        loss, predict = lenet.convolutional_neural_network(img, label)
        acc = layers.accuracy(predict, label)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    exe = fluid.Executor()
    exe.run(startup)

    reader = fluid.io.batch(mnist.train(), batch_size=args.batch,
                            drop_last=True)
    loader = fluid.io.DataLoader.from_generator(
        feed_list=[img, label], capacity=8)
    loader.set_sample_list_generator(reader)

    step = 0
    for epoch in range(args.epochs):
        if step >= 200:
            break
        for feed in loader:
            lv, av = exe.run(main_prog, feed=feed,
                             fetch_list=[loss, acc])
            step += 1
            if step % 50 == 0:
                print(f"epoch {epoch} step {step}: "
                      f"loss {np.asarray(lv).item():.4f} "
                      f"acc {np.asarray(av).item():.3f}")
            if step >= 200:  # synthetic corpus: a short run suffices
                break
    print("done:", step, "steps")


if __name__ == "__main__":
    main()

"""Long-context training with ring attention over a sequence-parallel
mesh — the fluid-API walkthrough of the framework's long-context axis
(SURVEY §5; reference scale-out analogue: ParallelExecutor + custom
attention kernels).

A tiny causal transformer trains on a shifted-copy task at seq 512
with the attention computed by `layers.ring_attention`: the sequence
dimension is sharded over the mesh's `sp` axis and K/V blocks rotate
via ppermute (XLA CollectivePermute over ICI on real hardware), so
per-device attention memory is O(T·T/sp), not O(T²).

Run on the 8-device virtual CPU mesh:
    python examples/long_context.py --cpu
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="run on 8 virtual CPU devices")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--seq", type=int, default=512)
    args = ap.parse_args()

    if args.cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from jax.sharding import Mesh

    import paddle_tpu as fluid
    from paddle_tpu import layers

    vocab, d, heads, t = 64, 32, 8, args.seq
    batch = 2
    sp = min(8, len(jax.devices()))
    mesh = Mesh(np.asarray(jax.devices()[:sp]).reshape(sp), ("sp",))

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 11
    with fluid.program_guard(main_prog, startup):
        tokens = layers.data("tokens", shape=[batch, t], dtype="int64",
                             append_batch_size=False)
        targets = layers.data("targets", shape=[batch, t], dtype="int64",
                              append_batch_size=False)
        emb = layers.embedding(tokens, size=[vocab, d])
        qkv = layers.fc(emb, size=3 * d, num_flatten_dims=2)
        q = layers.slice(qkv, axes=[2], starts=[0], ends=[d])
        k = layers.slice(qkv, axes=[2], starts=[d], ends=[2 * d])
        v = layers.slice(qkv, axes=[2], starts=[2 * d], ends=[3 * d])

        def heads_first(x):
            x = layers.reshape(x, shape=[batch, t, heads, d // heads])
            return layers.transpose(x, perm=[0, 2, 1, 3])

        # the long-context core: exact causal attention with K/V blocks
        # rotating around the mesh's sp axis
        ctxv = layers.ring_attention(heads_first(q), heads_first(k),
                                     heads_first(v), causal=True)
        ctxv = layers.transpose(ctxv, perm=[0, 2, 1, 3])
        ctxv = layers.reshape(ctxv, shape=[batch, t, d])
        h = layers.fc(ctxv, size=d, num_flatten_dims=2, act="relu")
        logits = layers.fc(h, size=vocab, num_flatten_dims=2)
        loss = layers.mean(layers.softmax_with_cross_entropy(
            logits, layers.reshape(targets, shape=[batch, t, 1])))
        fluid.optimizer.Adam(learning_rate=3e-3).minimize(loss)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        compiled = fluid.CompiledProgram(main_prog).with_distributed(
            mesh, batch_axes=())

        rng = np.random.RandomState(0)
        toks = rng.randint(0, vocab, (batch, t)).astype(np.int64)
        # shifted-copy task: predict the previous token
        tgt = np.roll(toks, 1, axis=1)
        first = last = None
        for step in range(args.steps):
            lv, = exe.run(compiled,
                          feed={"tokens": toks, "targets": tgt},
                          fetch_list=[loss])
            last = float(np.asarray(lv))
            if first is None:
                first = last
            if step % 10 == 0 or step == args.steps - 1:
                print(f"step {step:3d}  loss {last:.4f}  "
                      f"(seq {t}, sp={sp})")
    assert last < first, f"loss did not drop: {first} -> {last}"
    print(f"done: loss {first:.4f} -> {last:.4f} with ring attention "
          f"over sp={sp}")


if __name__ == "__main__":
    main()

"""BERT-base masked-LM-style pretraining step — the benchmark flagship
(bench.py config 3) as a runnable script.

    python examples/pretrain_bert.py [--cpu] [--tiny] [--steps N]

Shows: AMP bf16 (contrib.mixed_precision), the Pallas flash-attention
kernel, and state donation (parameters update in place at the XLA
buffer level, no per-step host copies).
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--tiny", action="store_true",
                    help="4-layer d=128 config for a quick local run")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer

    if args.tiny:
        cfg = transformer.TransformerConfig(
            vocab_size=1000, d_model=128, n_heads=4, n_layers=4,
            d_ff=512, dropout=0.1, attn_dropout=0.0)
    else:
        cfg = transformer.bert_base(dropout=0.1, attn_dropout=0.0)

    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        loss, feeds = transformer.build_train(cfg, args.batch, args.seq,
                                              lr=1e-4, amp=True)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        toks = rng.randint(0, cfg.vocab_size, (args.batch, args.seq)) \
            .astype(np.int64)
        feed = {"tokens": toks, "labels": toks}
        exe.run(main_prog, feed=feed, fetch_list=[loss])  # compile
        t0 = time.perf_counter()
        for i in range(args.steps):
            lv, = exe.run(main_prog, feed=feed, fetch_list=[loss])
            if (i + 1) % 5 == 0:
                print(f"step {i + 1}: loss {float(np.asarray(lv)):.4f}")
        dt = (time.perf_counter() - t0) / args.steps
    print(f"{args.batch * args.seq / dt:,.0f} tokens/s "
          f"({dt * 1e3:.1f} ms/step, includes host sync each step — "
          f"see bench.py for the RTT-amortized measurement)")


if __name__ == "__main__":
    main()

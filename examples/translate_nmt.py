"""Train a Transformer NMT model on a synthetic copy/reverse task —
the book/08.machine_translation tutorial shape on paddle_tpu
(reference: python/paddle/fluid/tests/book/test_machine_translation.py,
modernized to the Transformer-big architecture of BASELINE config 3).

    python examples/translate_nmt.py [--cpu] [--steps N] [--big]

The whole encoder-decoder step (cross-attention included) compiles to
ONE XLA computation; greedy decoding reuses the trained program cloned
for test.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (default: attached TPU)")
    ap.add_argument("--steps", type=int, default=120)
    ap.add_argument("--big", action="store_true",
                    help="full Transformer-big dims (default: tiny)")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.models import nmt

    vocab, src_len, trg_len, batch = 64, 12, 12, 16
    if args.big:
        cfg = nmt.transformer_big_nmt(vocab_size=vocab, dropout=0.1)
    else:
        cfg = nmt.TransformerConfig(vocab_size=vocab, d_model=64,
                                    n_heads=4, n_layers=2, d_ff=128,
                                    dropout=0.0, use_flash=False)

    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        loss, feeds = nmt.build_train(cfg, batch, src_len, trg_len,
                                      lr=3e-3, label_smooth_eps=0.0)
        exe = fluid.Executor()
        exe.run(startup)

        rng = np.random.RandomState(0)
        for step in range(args.steps):
            # task: target = source reversed (forces real cross-attention;
            # a copy task can be solved by position alone)
            src = rng.randint(2, vocab, (batch, src_len)).astype(np.int64)
            trg_full = src[:, ::-1]
            trg = np.concatenate(
                [np.ones((batch, 1), np.int64), trg_full], axis=1)
            lv, = exe.run(main_prog,
                          feed={"src_tokens": src, "trg_tokens": trg},
                          fetch_list=[loss])
            if step % 20 == 0 or step == args.steps - 1:
                print(f"step {step}: loss {float(np.asarray(lv)):.4f}",
                      flush=True)

        # greedy decode with the trained weights: a decode graph sharing
        # parameters through the scope (explicit param names in nmt.py
        # make cross-program weight sharing build-order independent)
        src = rng.randint(2, vocab, (batch, src_len)).astype(np.int64)
        trg = np.ones((batch, trg_len + 1), np.int64)
        dec_prog, dec_startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(dec_prog, dec_startup):
            from paddle_tpu import layers
            s = layers.data("src_tokens", shape=[batch, src_len],
                            dtype="int64", append_batch_size=False)
            t = layers.data("trg_in", shape=[batch, trg_len],
                            dtype="int64", append_batch_size=False)
            memory = nmt.encode(s, cfg)
            lg = nmt.decode(t, memory, cfg)
        dec_prog = dec_prog.clone(for_test=True)
        for pos in range(trg_len):
            lg_v, = exe.run(dec_prog,
                            feed={"src_tokens": src,
                                  "trg_in": trg[:, :trg_len]},
                            fetch_list=[lg])
            nxt = np.asarray(lg_v)[:, pos, :].argmax(-1)
            trg[:, pos + 1] = nxt
        acc = float((trg[:, 1:] == src[:, ::-1]).mean())
        print(f"greedy decode reversal accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()

"""Train a toy GPT on a synthetic cyclic corpus and generate from it
with KV-cache incremental decoding.

    python examples/generate_gpt.py [--cpu]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.models import gpt

    vocab, seq = 32, 16
    cfg = gpt.gpt_small(vocab_size=vocab, d_model=64, n_heads=4,
                        n_layers=2, d_ff=128, max_seq_len=seq,
                        dropout=0.0, use_flash=False)
    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        loss, logits, tokens = gpt.build_train(cfg, batch=8, seq_len=seq,
                                               lr=5e-3)
        exe = fluid.Executor()
        exe.run(startup)
        base = np.arange(seq) % vocab
        toks = np.stack([(base + i) % vocab for i in range(8)]) \
            .astype(np.int64)
        for i in range(80):
            lv, = exe.run(main_prog, feed={"tokens": toks},
                          fetch_list=[loss])
            if (i + 1) % 20 == 0:
                print(f"step {i + 1}: loss {float(np.asarray(lv)):.4f}")

        dec_main, dec_start = fluid.Program(), fluid.Program()
        with fluid.program_guard(dec_main, dec_start):
            tok_var, dec_logits, cache_names = gpt.build_decode_step(
                cfg, batch=1, max_seq=seq)

    prompt = [0, 1, 2, 3]
    out = gpt.kv_generate(exe, scope, dec_main, tok_var, dec_logits,
                          cache_names, prompt=prompt, max_new_tokens=8)
    print("prompt:      ", prompt)
    print("continuation:", out, "(expected: counting on by one)")


if __name__ == "__main__":
    main()

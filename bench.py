"""Benchmark: single-chip training-step throughput on real TPU.

Matches BASELINE.json: the primary metric is BERT-base pretraining
tokens/sec/chip (config 3); BENCH_MODEL=resnet50 measures the ResNet-50
ImageNet config (the north-star MFU workload, config 0). Each step
(fwd + vjp-backward + optimizer) is ONE XLA program produced by the
Executor. vs_baseline = measured MFU / 0.50 (the ">=50% MFU" north
star; the reference publishes no numeric baseline — BASELINE.md).

Prints ONE JSON line for the selected model (default: bert).
BENCH_MODEL selects bert | resnet50 | gpt (causal flash path) |
transformer (Transformer-big En-De NMT, config 3) | deeplab
(DeepLabv3+ dilated convs, config 5) | both (bert + resnet50) |
all (all five).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def _log_path() -> str:
    """Where result lines + monitor snapshots go (JSONL, append mode):
    BENCH_LOG env > FLAGS_monitor_export_path > bench_log.jsonl. Every
    record is flushed the moment it exists, so a harness timeout-kill
    (the BENCH_r05 `parsed: null` failure) can no longer lose completed
    configs."""
    p = os.environ.get("BENCH_LOG")
    if p:
        return p
    try:
        from paddle_tpu.core.flags import FLAGS
        if FLAGS.monitor_export_path:
            return FLAGS.monitor_export_path
    except Exception:  # noqa: BLE001 — log path must never kill bench
        pass
    return "bench_log.jsonl"


def _emit(log_path, record):
    """Append one JSON line to the log (and leave stdout untouched)."""
    try:
        with open(log_path, "a") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:
        print(f"# bench log write failed: {e}", file=sys.stderr)


def _summary_path() -> str:
    """The top-level JSON summary artifact (BENCH_SUMMARY env, default
    bench_summary.json). Unlike the JSONL log this is ONE json.load-able
    document: written ahead (status "running") before any bench starts
    and atomically replaced after every result, so the file parses at
    every instant of the run — including the instant `timeout -k` kills
    it (the BENCH_r05 rc=124/parsed:null failure mode)."""
    return os.environ.get("BENCH_SUMMARY", "bench_summary.json")


def _write_summary(path, obj):
    """Atomic replace (tmp + fsync + os.replace): readers never observe
    a torn or truncated summary."""
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError as e:
        print(f"# bench summary write failed: {e}", file=sys.stderr)


def _flight_path() -> str:
    """Crash flight-recorder dump target: BENCH_FLIGHT env >
    FLAGS_flight_recorder_path > bench_flight.jsonl."""
    p = os.environ.get("BENCH_FLIGHT")
    if p:
        return p
    try:
        from paddle_tpu.core.flags import FLAGS
        if FLAGS.flight_recorder_path:
            return FLAGS.flight_recorder_path
    except Exception:  # noqa: BLE001 — path lookup must never kill bench
        pass
    return "bench_flight.jsonl"


def _perf_ledger():
    """Import tools/perf_ledger.py (lightweight: no paddle_tpu/jax
    import) for provenance stamping and the BENCH_LEDGER hook."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    import perf_ledger
    return perf_ledger


def _ledger_and_gate(summary, log, platform_hint=""):
    """BENCH_LEDGER=path.jsonl auto-ingests this run's results into
    the longitudinal perf ledger (provenance stamped); BENCH_GATE=1
    additionally gates them against the EXISTING history first
    (tools/perf_gate.py) and emits the perf_gate record to stdout +
    the JSONL log. Informational: bench's exit code stays the
    one-artifact-per-model contract — CI that wants a failing gate
    runs tools/perf_gate.py on the summary itself."""
    ledger = os.environ.get("BENCH_LEDGER", "")
    if not ledger:
        return
    try:
        pl = _perf_ledger()
        rows, _skipped = pl.rows_from_record(summary)
        if not rows:
            return
        if os.environ.get("BENCH_GATE") == "1":
            import perf_gate
            results = perf_gate.gate_rows(rows, pl.load_rows(ledger))
            report = perf_gate.gate_report(results, ledger, 4.0, 3, 20)
            print(json.dumps(report), flush=True)
            _emit(log, report)
        pl.append_rows(ledger, rows,
                       pl.provenance(platform=platform_hint or None))
    except Exception as e:  # noqa: BLE001 — ledger must never kill bench
        print(f"# perf ledger unavailable: {e}", file=sys.stderr)


def _record_bench_stats(flops_per_step):
    """Feed the monitor the model's per-step flops + the chip peak so
    tools/metrics_report.py can derive MFU from the step-time histogram
    (no-ops unless FLAGS_enable_monitor)."""
    try:
        from paddle_tpu import monitor
        if not monitor.enabled():
            return
        monitor.STAT_SET("bench.model_flops_per_step", flops_per_step)
        monitor.STAT_SET("bench.peak_flops_per_chip",
                         peak_flops_per_chip())
    except Exception:  # noqa: BLE001 — stats must never kill bench
        pass


def peak_flops_per_chip():
    """bf16 peak for the local chip; v5e = 197 TFLOP/s."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v4" in kind:
        return 275e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    return 197e12


def model_flops_per_token(cfg, seq_len):
    """Matmul flops per token, fwd+bwd (3x fwd): dense 6*N_mat +
    attention 12*L*T*d (scores+context, fwd+bwd). The vocab projection
    counts only at the positions it actually runs on (mask_frac < 1
    under the MLM objective, where the lm head is gathered to the
    masked positions) — MFU stays honest about work NOT done."""
    d, L = cfg.d_model, cfg.n_layers
    n_mat = (L * (4 * d * d + 2 * d * cfg.d_ff)
             + getattr(cfg, "mask_frac", 1.0) * cfg.vocab_size * d)
    dense = 6 * n_mat
    attn = 12 * L * seq_len * d
    return dense + attn


def _timed_steps(exe, prog, feed, loss, steps):
    """Device step time with host/transport latency amortized out.

    The chip may sit behind a remote tunnel where every device→host
    sync costs a full round trip (measured ~70-110 ms here — 2-5x a
    whole training step). Fetching the loss to numpy every iteration
    (the naive loop) therefore measures the network, not the TPU.
    Instead: enqueue `steps` async steps (they serialize on-device via
    the donated state dict), sync ONCE at the end, and subtract one
    measured sync RTT. On a locally attached device rtt ~= 0 and this
    degrades to plain wall-clock timing.

    RTT is the median of 5 probes (the tunnel jitters 70-110 ms; a
    single sample puts +-4% on a 30-step window), and the measurement
    runs as TWO independent windows whose relative spread is reported,
    so round-over-round MFU deltas carry an error bar.

    Returns (dt_seconds, last_loss, stats_dict).
    """
    import jax
    import jax.numpy as jnp

    # BENCH_MESH ('8' dp-only, '4,2' dp x tp): run the step through the
    # GSPMD sharded path — a SpecLayout table over the mesh (ZeRO
    # moments on the data axis, params on the model axis, feeds batch-
    # sharded), one compile per signature exactly like the single-chip
    # path. Ledger rows then report tok/s/chip next to the single-chip
    # numbers (docs/sharding.md).
    mesh_env = os.environ.get("BENCH_MESH", "")
    mesh = layout = None
    run_prog = prog
    if mesh_env:
        from paddle_tpu.compiler import CompiledProgram
        from paddle_tpu.parallel.layout import SpecLayout, mesh_from_spec
        mesh = mesh_from_spec(mesh_env)
        layout = SpecLayout(mesh).add_program(prog)
        run_prog = CompiledProgram(prog).with_distributed(
            mesh, state_spec_fn=layout,
            batch_axes=(layout.data_axis,) if layout.data_axis else ())

    # Stage the batch on device ONCE: the executor passes jax.Array
    # feeds straight to the jitted step, so the timed loop measures the
    # training step, not a per-step host->device reupload of the batch
    # (38 MB/step for ResNet images — behind the tunnel that transfer
    # alone is seconds, 30x the step itself; a production input
    # pipeline double-buffers batches onto device the same way,
    # reference reader/buffered_reader.cc). Under a mesh each batch is
    # device_put straight into its batch-sharded layout, so no chip
    # ever holds the full host batch.
    def _stage(v):
        arr = np.asarray(v)
        ns = run_prog.feed_sharding(arr.shape) if mesh is not None \
            else None
        return jax.device_put(arr, ns) if ns is not None \
            else jax.device_put(arr)
    feed = {k: _stage(v) for k, v in feed.items()}

    # Record what the graph-optimization pipeline does to this program
    # (FLAGS_graph_opt_level, analysis/passes): the gate memoizes per
    # (fingerprint, level, feeds, fetches), so this primes the exact
    # entry the executor reuses below — the pipeline runs once, not
    # twice. opt0-vs-opt2 sweep pairs diff these extras.
    from paddle_tpu.analysis import optimize_gate
    from paddle_tpu.core.flags import FLAGS
    opt_level = int(FLAGS.graph_opt_level)
    ops_pre = len(prog.global_block().ops)
    opt_prog, _ = optimize_gate(
        prog, feed_names=sorted(feed.keys()),
        fetch_names=[loss.name], where="bench")
    ops_post = len(opt_prog.global_block().ops)

    # Static peak estimate of the program the executor will actually
    # compile, sized with the concrete feed shapes — recorded next to
    # the measured device stats below so every ledger row calibrates
    # the estimator (analysis/memory, docs/memory_planning.md).
    est_peak = est_dynamic = None
    try:
        from paddle_tpu.analysis import analyze_program_memory
        _plan = analyze_program_memory(
            opt_prog, feed_names=sorted(feed.keys()),
            fetch_names=[loss.name],
            feed_shapes={k: (tuple(v.shape), str(v.dtype))
                         for k, v in feed.items()})
        est_peak = int(_plan.peak_bytes)
        est_dynamic = bool(_plan.dynamic)
    except Exception as e:  # noqa: BLE001 — never fail a bench run
        print(f"# memory estimate unavailable: {type(e).__name__}: {e}",
              file=sys.stderr)

    # compile + warmup (synced)
    exe.run(run_prog, feed=feed, fetch_list=[loss])
    x, = exe.run(run_prog, feed=feed, fetch_list=[loss],
                 return_numpy=False)
    np.asarray(x)  # drain the queue
    np.asarray(jnp.zeros(()) + 1)  # compile the probe expression
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        # fresh tiny device value: queue is empty and the probe is
        # already compiled, so fetching it is one pure host<->device
        # round trip (np.asarray on an already-fetched array would hit
        # the cached host copy and measure ~0)
        np.asarray(jnp.zeros(()) + 1)
        rtts.append(time.perf_counter() - t0)
    rtt = float(np.median(rtts))

    def window(n):
        t0 = time.perf_counter()
        for _ in range(n):
            x, = exe.run(run_prog, feed=feed, fetch_list=[loss],
                         return_numpy=False)
        lv = np.asarray(x)
        elapsed = time.perf_counter() - t0
        # never let the RTT subtraction zero out (or flip the sign of)
        # the measurement — a tiny model behind a slow tunnel could
        # otherwise print negative tokens/s
        return max(elapsed - rtt, 0.05 * elapsed) / n, lv

    n1 = max(1, steps // 2)
    n2 = max(1, steps - n1)
    dt1, _ = window(n1)
    dt2, lv = window(n2)
    dt = (dt1 * n1 + dt2 * n2) / (n1 + n2)
    stats = {"rtt_ms": round(rtt * 1000, 1),
             "windows_ms": [round(dt1 * 1000, 2), round(dt2 * 1000, 2)],
             "window_spread": round(abs(dt1 - dt2) / dt, 4),
             "graph_opt_level": opt_level,
             "ops_pre_opt": ops_pre, "ops_post_opt": ops_post}
    if mesh is not None:
        stats["mesh_shape"] = [int(mesh.shape[a])
                               for a in mesh.axis_names]
        stats["mesh_axes"] = list(mesh.axis_names)
        stats["mesh_devices"] = int(mesh.size)
        stats["collective_bytes_per_step"] = \
            int(layout.collective_bytes_estimate(prog))
        # closed-form gradient-sync reference (arxiv 2004.13336): the
        # perf ledger flags drift between this and the per-op model's
        # prediction above
        stats["grad_sync_bytes_per_step"] = \
            int(layout.gradient_sync_bytes(prog))
    if est_peak is not None:
        stats["est_peak_bytes"] = est_peak
        stats["est_peak_dynamic"] = est_dynamic
        # measured counterpart: PJRT per-device stats after the timed
        # windows (empty {} on backends that don't report, e.g. CPU)
        from paddle_tpu.core.memory import device_memory_stats
        mem = device_memory_stats()
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if mem.get(key) is not None:
                stats[f"measured_{key}"] = int(mem[key])
    return dt, lv, stats


def _bench_layers(n_layers=None):
    """Optional depth override (BENCH_LAYERS env or explicit arg): the
    CPU-validate path compiles a 2-layer model so certifying the bench
    code path costs seconds, not the minute+ a 12-layer XLA CPU compile
    takes. Unset -> each model's reference depth."""
    if n_layers is not None:
        return {"n_layers": int(n_layers)}
    env = os.environ.get("BENCH_LAYERS", "")
    return {"n_layers": int(env)} if env else {}


def _bench_flash_blocks():
    """BENCH_FLASH_BLOCK env -> explicit flash tile attrs on the model
    config: "512" pins block_q=block_k=512, "512,256" pins q,k
    separately. Unset -> {} so the op attrs stay absent and the
    flags/autotuner choose the tile (ops/pallas/autotune.py)."""
    env = os.environ.get("BENCH_FLASH_BLOCK", "")
    if not env:
        return {}
    parts = [int(p) for p in env.split(",") if p.strip()]
    if not parts:
        return {}
    bq = parts[0]
    bk = parts[1] if len(parts) > 1 else parts[0]
    return {"flash_block_q": bq, "flash_block_k": bk}


def build_bert_bench(batch=None, seq_len=None, n_layers=None):
    """Build the BERT pretraining step per the BENCH_* env config.
    Returns (exe, program, scope, feed, loss, cfg) — shared by bench.py
    and tools/profile_step.py so the profiled program is exactly the
    benchmarked one."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer

    batch = batch or int(os.environ.get("BENCH_BATCH", "32"))
    seq_len = seq_len or int(os.environ.get("BENCH_SEQ", "512"))
    amp = os.environ.get("BENCH_AMP", "1") == "1"
    use_flash = os.environ.get("BENCH_FLASH", "1") == "1"
    mlm = os.environ.get("BENCH_MLM", "0") == "1"
    cfg = transformer.bert_base(dropout=0.1, attn_dropout=0.0,
                                use_flash=use_flash,
                                **_bench_flash_blocks(),
                                **_bench_layers(n_layers))
    # BERT's actual objective: predict the ~15% masked positions, not
    # all T (rounded up to a multiple of 8 for clean TPU tiling)
    n_mask = -(-int(seq_len * 0.15) // 8) * 8
    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        if mlm:
            loss, feeds = transformer.build_train_mlm(
                cfg, batch, seq_len, n_mask, lr=1e-4, amp=amp)
        else:
            loss, feeds = transformer.build_train(cfg, batch, seq_len,
                                                  lr=1e-4, amp=amp)
        exe = fluid.Executor()
        exe.run(startup)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, cfg.vocab_size, (batch, seq_len)).astype(np.int64)
    if mlm:
        pos = np.stack([rng.choice(seq_len, n_mask, replace=False)
                        + i * seq_len for i in range(batch)])
        pos = pos.reshape(-1).astype(np.int32)
        feed = {"tokens": toks, "mask_pos": pos,
                "mask_label": toks.reshape(-1)[pos].reshape(-1, 1)}
        cfg.mask_frac = n_mask / seq_len
    else:
        feed = {"tokens": toks, "labels": toks}
        cfg.mask_frac = 1.0
    return exe, main_prog, scope, feed, loss, cfg


def build_resnet50_bench(batch=None):
    """ResNet-50 ImageNet step per the BENCH_* env config; same return
    contract as build_bert_bench (cfg slot is None)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    batch = batch or int(os.environ.get("BENCH_BATCH", "64"))
    amp = os.environ.get("BENCH_AMP", "1") == "1"
    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        loss, acc, feeds = resnet.build_train(amp=amp)
        exe = fluid.Executor()
        exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"image": rng.randn(batch, 3, 224, 224).astype(np.float32),
            "label": rng.randint(0, 1000, (batch, 1)).astype(np.int64)}
    return exe, main_prog, scope, feed, loss, None


def bench_bert():
    import paddle_tpu as fluid

    steps = int(os.environ.get("BENCH_STEPS", "20"))
    prior_flash = os.environ.get("BENCH_FLASH")
    probes_ms = None
    try:
        if prior_flash is None:
            # unset: probe both attention implementations briefly and
            # run the full measurement with the winner (the framework's
            # job is the fastest correct step, not a fixed kernel
            # choice)
            probes = {}
            for flag in ("1", "0"):
                os.environ["BENCH_FLASH"] = flag
                exe, prog, scope, feed, loss, cfg = build_bert_bench()
                with fluid.scope_guard(scope):
                    dt, _, _ = _timed_steps(exe, prog, feed, loss,
                                            max(4, steps // 4))
                probes[flag] = dt
                exe.close()
            best = min(probes, key=probes.get)
            os.environ["BENCH_FLASH"] = best
            probes_ms = {k: round(v * 1000, 2) for k, v in probes.items()}
        exe, main_prog, scope, feed, loss, cfg = build_bert_bench()
        flash_used = os.environ.get("BENCH_FLASH", "1")
        batch, seq_len = feed["tokens"].shape
        with fluid.scope_guard(scope):
            dt, lv, stats = _timed_steps(exe, main_prog, feed, loss, steps)
    finally:
        # the probe must not leak its winner into later benches
        # (BENCH_MODEL=all runs gpt after bert with its own default)
        if prior_flash is None:
            os.environ.pop("BENCH_FLASH", None)
        else:
            os.environ["BENCH_FLASH"] = prior_flash

    tokens_per_sec = batch * seq_len / dt
    flops = model_flops_per_token(cfg, seq_len) * batch * seq_len
    mfu = flops / dt / peak_flops_per_chip()
    _record_bench_stats(flops)
    extra = {"step_ms": round(dt * 1000, 2), "mfu": round(mfu, 4),
             "batch": batch, "seq_len": seq_len,
             "flash": flash_used,
             "flash_block": os.environ.get("BENCH_FLASH_BLOCK", "auto"),
             "loss": float(np.asarray(lv)),
             "mlm": os.environ.get("BENCH_MLM", "0"), **stats}
    if probes_ms is not None:
        extra["flash_probe_ms"] = probes_ms
    if stats.get("mesh_devices"):
        extra["tok_s_per_chip"] = round(
            tokens_per_sec / stats["mesh_devices"], 1)
    return {
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.50, 4),
        "extra": extra,
    }


def bench_resnet50():
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    steps = int(os.environ.get("BENCH_STEPS", "20"))
    exe, main_prog, scope, feed, loss, _ = build_resnet50_bench()
    batch = feed["image"].shape[0]
    with fluid.scope_guard(scope):
        dt, lv, stats = _timed_steps(exe, main_prog, feed, loss, steps)

    images_per_sec = batch / dt
    flops = 3 * resnet.flops_per_image() * batch  # fwd + 2x bwd
    mfu = flops / dt / peak_flops_per_chip()
    _record_bench_stats(flops)
    return {
        "metric": "resnet50_imagenet_images_per_sec_per_chip",
        "value": round(images_per_sec, 1),
        "unit": "images/s",
        "vs_baseline": round(mfu / 0.50, 4),
        "extra": {"step_ms": round(dt * 1000, 2), "mfu": round(mfu, 4),
                  "batch": batch, "loss": float(np.asarray(lv)), **stats},
    }


def build_gpt_bench(batch=None, seq_len=None, n_layers=None):
    """GPT-small causal-LM step per the BENCH_* env config (third
    headline workload: exercises the causal flash-kernel path)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import gpt

    batch = batch or int(os.environ.get("BENCH_BATCH", "32"))
    seq_len = seq_len or int(os.environ.get("BENCH_SEQ", "512"))
    amp = os.environ.get("BENCH_AMP", "1") == "1"
    use_flash = os.environ.get("BENCH_FLASH", "1") == "1"
    cfg = gpt.gpt_small(dropout=0.1, attn_dropout=0.0,
                        use_flash=use_flash, max_seq_len=seq_len,
                        **_bench_flash_blocks(),
                        **_bench_layers(n_layers))
    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        loss, logits, tokens = gpt.build_train(cfg, batch, seq_len,
                                               lr=3e-4, amp=amp)
        exe = fluid.Executor()
        exe.run(startup)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, cfg.vocab_size, (batch, seq_len)).astype(np.int64)
    return exe, main_prog, scope, {"tokens": toks}, loss, cfg


def bench_gpt():
    import paddle_tpu as fluid

    steps = int(os.environ.get("BENCH_STEPS", "20"))
    exe, main_prog, scope, feed, loss, cfg = build_gpt_bench()
    batch, seq_len = feed["tokens"].shape
    with fluid.scope_guard(scope):
        dt, lv, stats = _timed_steps(exe, main_prog, feed, loss, steps)
    t_eff = seq_len - 1  # in-graph next-token shift
    tokens_per_sec = batch * t_eff / dt
    # causal attention does half the score/context flops: subtract half
    # of the attention term from the shared full-attention accounting
    flops_tok = model_flops_per_token(cfg, t_eff) \
        - 6 * cfg.n_layers * t_eff * cfg.d_model
    flops = flops_tok * batch * t_eff
    mfu = flops / dt / peak_flops_per_chip()
    _record_bench_stats(flops)
    extra = {"step_ms": round(dt * 1000, 2), "mfu": round(mfu, 4),
             "batch": int(batch), "seq_len": int(seq_len),
             "loss": float(np.asarray(lv)), **stats}
    if stats.get("mesh_devices"):
        extra["tok_s_per_chip"] = round(
            tokens_per_sec / stats["mesh_devices"], 1)
    return {
        "metric": "gpt_small_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.50, 4),
        "extra": extra,
    }


def build_transformer_bench(batch=None, src_len=None, trg_len=None,
                            n_layers=None):
    """Transformer-big En-De NMT step (BASELINE config 3); same return
    contract as build_bert_bench."""
    import paddle_tpu as fluid
    from paddle_tpu.models import nmt

    batch = batch or int(os.environ.get("BENCH_BATCH", "32"))
    src_len = src_len or int(os.environ.get("BENCH_SEQ", "256"))
    trg_len = trg_len or src_len
    amp = os.environ.get("BENCH_AMP", "1") == "1"
    use_flash = os.environ.get("BENCH_FLASH", "1") == "1"
    cfg = nmt.transformer_big_nmt(dropout=0.1, attn_dropout=0.0,
                                  use_flash=use_flash,
                                  **_bench_flash_blocks(),
                                  **_bench_layers(n_layers))
    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        loss, feeds = nmt.build_train(cfg, batch, src_len, trg_len,
                                      lr=1e-4, amp=amp)
        exe = fluid.Executor()
        exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {
        "src_tokens": rng.randint(0, cfg.vocab_size,
                                  (batch, src_len)).astype(np.int64),
        "trg_tokens": rng.randint(0, cfg.vocab_size,
                                  (batch, trg_len + 1)).astype(np.int64),
    }
    return exe, main_prog, scope, feed, loss, cfg


def bench_transformer():
    import paddle_tpu as fluid
    from paddle_tpu.models import nmt

    steps = int(os.environ.get("BENCH_STEPS", "20"))
    exe, main_prog, scope, feed, loss, cfg = build_transformer_bench()
    batch, src_len = feed["src_tokens"].shape
    trg_len = feed["trg_tokens"].shape[1] - 1
    with fluid.scope_guard(scope):
        dt, lv, stats = _timed_steps(exe, main_prog, feed, loss, steps)
    tokens_per_sec = batch * trg_len / dt
    flops = nmt.flops_per_step(cfg, batch, src_len, trg_len)
    mfu = flops / dt / peak_flops_per_chip()
    _record_bench_stats(flops)
    extra = {"step_ms": round(dt * 1000, 2), "mfu": round(mfu, 4),
             "batch": int(batch), "src_len": int(src_len),
             "trg_len": int(trg_len),
             "loss": float(np.asarray(lv)), **stats}
    if stats.get("mesh_devices"):
        extra["tok_s_per_chip"] = round(
            tokens_per_sec / stats["mesh_devices"], 1)
    return {
        "metric": "transformer_big_ende_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.50, 4),
        "extra": extra,
    }


def build_deeplab_bench(batch=None, img_hw=None):
    """DeepLabv3+ Cityscapes step (BASELINE config 5 — dilated convs +
    large activations); same return contract as build_bert_bench."""
    import paddle_tpu as fluid
    from paddle_tpu.models import deeplab

    batch = batch or int(os.environ.get("BENCH_BATCH", "8"))
    img_hw = img_hw or int(os.environ.get("BENCH_IMG", "513"))
    amp = os.environ.get("BENCH_AMP", "1") == "1"
    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        loss, feeds = deeplab.build_train(img_hw=img_hw, batch=batch,
                                          amp=amp)
        exe = fluid.Executor()
        exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {
        "image": rng.randn(batch, 3, img_hw, img_hw).astype(np.float32),
        "label": rng.randint(0, deeplab.N_CLASSES,
                             (batch, img_hw, img_hw)).astype(np.int64),
    }
    return exe, main_prog, scope, feed, loss, None


def bench_deeplab():
    import paddle_tpu as fluid
    from paddle_tpu.models import deeplab

    steps = int(os.environ.get("BENCH_STEPS", "20"))
    exe, main_prog, scope, feed, loss, _ = build_deeplab_bench()
    batch = feed["image"].shape[0]
    img_hw = feed["image"].shape[2]
    with fluid.scope_guard(scope):
        dt, lv, stats = _timed_steps(exe, main_prog, feed, loss, steps)
    images_per_sec = batch / dt
    flops = 3 * deeplab.flops_per_image(img_hw) * batch  # fwd + 2x bwd
    mfu = flops / dt / peak_flops_per_chip()
    _record_bench_stats(flops)
    return {
        "metric": "deeplabv3p_cityscapes_images_per_sec_per_chip",
        "value": round(images_per_sec, 1),
        "unit": "images/s",
        "vs_baseline": round(mfu / 0.50, 4),
        "extra": {"step_ms": round(dt * 1000, 2), "mfu": round(mfu, 4),
                  "batch": int(batch), "img_hw": int(img_hw),
                  "loss": float(np.asarray(lv)), **stats},
    }


_PROBE_CODE = """
import jax, numpy as np, jax.numpy as jnp
d = jax.devices()
assert d and d[0].platform == 'tpu', d
np.asarray(jnp.zeros(()) + 1)
"""

_CPU_VALIDATE_CODE = """
import jax
jax.config.update('jax_platforms', 'cpu')
import os, sys
sys.path.insert(0, {root!r})
os.environ['BENCH_FLASH'] = '0'
import bench
import paddle_tpu as fluid
from paddle_tpu import monitor
# with FLAGS_enable_monitor inherited from the parent env, the tiny run
# below accumulates executor step/compile/feed stats in THIS process;
# the periodic exporter flushes them even if the parent's deadline
# kills us mid-run, and the explicit snapshot covers the clean exit
if monitor.enabled() and {log!r}:
    monitor.start_exporter({log!r}, interval=3.0)
exe, prog, scope, feed, loss, cfg = bench._CPU_TINY_BUILDS[{model!r}]()
with fluid.scope_guard(scope):
    dt, lv, stats = bench._timed_steps(exe, prog, feed, loss, 2)
import math
assert math.isfinite(float(lv)), 'non-finite loss'
if monitor.enabled() and {log!r}:
    monitor.stop_exporter(flush=True)
print('cpu ok', dt, float(lv))
"""

# tiny-shape builders used by the wedge-path CPU validation: certify
# the SELECTED model's bench code path, not just BERT's. Transformer
# families validate at 2 layers — the layer loop is homogeneous, and a
# 12-layer fwd+bwd XLA CPU compile alone (~60s) would blow a tight
# --time-budget before any stats exist.
_CPU_TINY_BUILDS = {
    "bert": lambda: build_bert_bench(batch=2, seq_len=64, n_layers=2),
    "resnet50": lambda: build_resnet50_bench(batch=2),
    "gpt": lambda: build_gpt_bench(batch=2, seq_len=64, n_layers=2),
    "transformer": lambda: build_transformer_bench(batch=2, src_len=32,
                                                   trg_len=24,
                                                   n_layers=2),
    "deeplab": lambda: build_deeplab_bench(batch=1, img_hw=65),
}


def _probe_backend(budget_left=None):
    """Decide whether the TPU backend is reachable WITHOUT letting a
    wedged tunnel block bench.py past its deadline.

    A wedged tunnel makes `jax.devices()` block for many minutes
    inside the PJRT C API (round 3: two init attempts burned 25 min
    and the driver timeout-killed the whole bench → unparseable
    artifact). So the probe runs in a SUBPROCESS: if it hasn't
    answered by the deadline we stop waiting and report unavailable —
    but we never kill it (timeout-killing a TPU process mid-claim is
    itself a known wedge trigger); the orphan is left to finish or
    fail on its own.

    `budget_left` (seconds, from --time-budget) caps the wait so the
    probe alone can never exhaust the run's budget.

    Returns (ok, detail).
    """
    wait = float(os.environ.get("BENCH_WAIT_TPU_S", "180"))
    if budget_left is not None:
        # leave at least half the budget for actual benching
        wait = max(5.0, min(wait, budget_left * 0.5))
    deadline = time.time() + wait
    attempt = 0
    while True:
        attempt += 1
        p = subprocess.Popen([sys.executable, "-c", _PROBE_CODE],
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL,
                             start_new_session=True)
        while time.time() < deadline:
            rc = p.poll()
            if rc is not None:
                break
            time.sleep(2)
        rc = p.poll()
        if rc == 0:
            return True, f"probe ok (attempt {attempt})"
        if rc is None:
            return False, ("backend unavailable: probe still blocked at "
                           "deadline (left running, not killed)")
        # failed fast: retry only while a ~20s backoff still fits before
        # the deadline, so we never spawn a probe doomed to be reported
        # as 'blocked' (and keep the real rc in the failure detail).
        # Under an explicit --time-budget a fast rc!=0 (no TPU runtime
        # at all) is decisive — backoff retries ride out tunnel flake,
        # and here they'd only starve the CPU-validate fallback.
        if budget_left is not None or time.time() + 20 >= deadline:
            return False, (f"backend unavailable: probe exited rc={rc} "
                           f"after {attempt} attempt(s)")
        time.sleep(20)


def _cpu_validate(models, budget_left=None, log_path=""):
    """Run a tiny bench step of each model on CPU, all subprocesses in
    parallel under ONE shared deadline, to certify the bench code paths
    work even when the chip is unreachable. CPU-only children — safe to
    kill at the deadline (no tunnel claim). Returns {model: bool}."""
    root = os.path.dirname(os.path.abspath(__file__))
    wait = float(os.environ.get("BENCH_CPU_VALIDATE_S", "300"))
    if budget_left is not None:
        wait = max(10.0, min(wait, budget_left))
    deadline = time.time() + wait
    procs = {}
    for m in dict.fromkeys(models):
        code = _CPU_VALIDATE_CODE.format(root=root, model=m,
                                         log=log_path)
        try:
            procs[m] = subprocess.Popen(
                [sys.executable, "-c", code],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        except OSError:
            procs[m] = None
    ok = {}
    for m, p in procs.items():
        if p is None:
            ok[m] = False
            continue
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
        ok[m] = p.poll() == 0
    return ok


_METRICS = {
    "bert": ("bert_base_pretrain_tokens_per_sec_per_chip", "tokens/s"),
    "resnet50": ("resnet50_imagenet_images_per_sec_per_chip", "images/s"),
    "gpt": ("gpt_small_pretrain_tokens_per_sec_per_chip", "tokens/s"),
    "transformer": ("transformer_big_ende_tokens_per_sec_per_chip",
                    "tokens/s"),
    "deeplab": ("deeplabv3p_cityscapes_images_per_sec_per_chip",
                "images/s"),
}


def _error_line(model, err, cpu_validated=None):
    metric, unit = _METRICS[model]
    out = {"metric": metric, "value": 0.0, "unit": unit,
           "vs_baseline": 0.0, "error": err}
    if cpu_validated is not None:
        out["cpu_validated"] = cpu_validated
    return out


def _partial_lines(models, done, reason):
    """Result lines owed when the run is cut short (SIGTERM from the
    harness `timeout -k`, etc.): one error line per model that has not
    printed yet, plus a bench_partial_summary record. Pure function so
    the signal path is unit-testable (the real handler os._exits)."""
    done = set(done)
    lines = [_error_line(m, reason) for m in models if m not in done]
    summary = {"kind": "bench_partial_summary",
               "models": list(models),
               "completed": [m for m in models if m in done],
               "reason": reason}
    return lines, summary


def main(argv=None):
    """Always prints exactly one parseable JSON line per selected
    model, even when the TPU tunnel is wedged or a bench crashes — a
    missing artifact is strictly worse than an error artifact. Every
    result line is ALSO appended to the JSONL log the moment it exists
    (with monitor snapshots interleaved when FLAGS_enable_monitor),
    and --time-budget stops the run cleanly between configs before an
    external `timeout` can kill it mid-config."""
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--time-budget", type=float,
                    default=float(os.environ.get("BENCH_TIME_BUDGET",
                                                 "0")),
                    help="soft wall-clock cap in seconds (0 = none): "
                         "bench stops cleanly between configs once "
                         "exceeded, emitting skip lines for the rest")
    args = ap.parse_args(argv)
    t_start = time.time()
    deadline = t_start + args.time_budget if args.time_budget > 0 else None

    def budget_left():
        return None if deadline is None else deadline - time.time()

    model = os.environ.get("BENCH_MODEL", "bert")
    models = {"both": ["bert", "resnet50"],
              "all": ["bert", "resnet50", "gpt", "transformer",
                      "deeplab"]}.get(model, [model])
    models = [m for m in models if m in _METRICS] or ["bert"]

    # BENCH_PLATFORM=cpu runs the whole bench in-process on the forced
    # backend (no TPU probe, no CPU-validate subprocesses) — used by the
    # kill-resilience test and for plumbing work without a chip
    forced_platform = os.environ.get("BENCH_PLATFORM", "")
    if forced_platform:
        try:
            import jax
            jax.config.update("jax_platforms", forced_platform)
        except Exception as e:  # noqa: BLE001 — leave the default backend
            print(f"# BENCH_PLATFORM={forced_platform} failed: {e}",
                  file=sys.stderr)

    if args.time_budget <= 0 and not forced_platform \
            and "BENCH_TIME_BUDGET" not in os.environ:
        # The round driver runs plain `python bench.py` (TPU path)
        # under an external `timeout -k 10 870`: self-budget safely
        # below that so the run ends cleanly between configs with a
        # parseable artifact instead of dying rc=124 with parsed:null
        # (the BENCH_r03/r05 failure mode). Forced-platform runs (CPU
        # tests, plumbing work) keep the no-budget default.
        args.time_budget = float(os.environ.get(
            "BENCH_DEFAULT_TIME_BUDGET", "840"))
        deadline = t_start + args.time_budget
        print(f"# time budget defaulted to {args.time_budget:.0f}s "
              f"(set BENCH_TIME_BUDGET to override)", file=sys.stderr)

    log = _log_path()
    flight = _flight_path()
    summary_path = _summary_path()
    done = set()
    results = []
    # goodput ledger (FLAGS_enable_goodput): classify the whole bench
    # run's wall-clock — backend-probe wait and warmup compiles land in
    # their own categories (a probe-blocked rc=124 round shows up as
    # probe_wait instead of opaque lost time) and the category table is
    # stamped into bench_summary.json by _finalize_summary below
    _goodput = None
    try:
        from paddle_tpu import goodput as _gp
        if _gp.start_run("bench") is not None:
            _goodput = _gp
    except Exception as e:  # noqa: BLE001 — goodput must never kill bench
        print(f"# goodput unavailable: {e}", file=sys.stderr)
    # write-ahead: the artifact parses before the first model starts
    summary = {"kind": "bench_summary", "status": "running",
               "models": list(models), "completed": [], "results": [],
               "ts_start": t_start}
    # run provenance (git rev / platform / mesh) rides in the summary
    # so a ledger row ingested from this artifact is bisectable
    try:
        pl = _perf_ledger()
        summary.update(pl.provenance(platform=forced_platform or None))
    except Exception as e:  # noqa: BLE001 — provenance is best-effort
        print(f"# provenance unavailable: {e}", file=sys.stderr)
    _write_summary(summary_path, summary)

    def _finalize_summary(status, reason=None):
        summary["status"] = status
        summary["completed"] = [m for m in models if m in done]
        summary["results"] = results
        if reason is not None:
            summary["reason"] = reason
        if _goodput is not None:
            snap = _goodput.snapshot()
            if snap is not None:
                summary["goodput"] = {
                    "wall_s": snap["wall_s"],
                    "goodput_frac": snap["goodput_frac"],
                    "sum_frac_err": snap["sum_frac_err"],
                    "categories": snap["categories"],
                    "steps": snap["steps"],
                    "post_warmup_compiles": snap["post_warmup_compiles"],
                    "starved_steps": snap["starved_steps"]}
        summary["ts_end"] = time.time()
        _write_summary(summary_path, summary)

    def _on_term(signum, frame):
        # the harness runs bench under `timeout -k`: SIGTERM arrives
        # first, so flush error lines for every unfinished model plus a
        # summary before the follow-up SIGKILL — the artifact stays one
        # parseable line per selected model no matter where we died
        reason = f"killed: signal {signum} before completion"
        lines, partial = _partial_lines(models, done, reason)
        for line in lines:
            print(json.dumps(line), flush=True)
            _emit(log, {"kind": "bench_result", "ts": time.time(),
                        **line})
            results.append(line)
        partial["ts"] = time.time()
        print(json.dumps(partial), flush=True)
        _emit(log, partial)
        _finalize_summary("killed", reason=reason)
        try:
            from paddle_tpu import monitor
            monitor.dump_flight_recorder(flight,
                                         reason=f"signal {signum}")
        except Exception:  # noqa: BLE001 — dying anyway
            pass
        os._exit(128 + signum)

    try:
        import signal
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass
    monitor_on = False
    try:
        from paddle_tpu import monitor
        monitor_on = monitor.enabled()
        if monitor_on:
            # periodic crash-safe snapshots: even a run killed by the
            # harness timeout leaves step/compile/feed stats behind
            monitor.start_exporter(log)
        # post-mortems for crashes the SIGTERM path can't see (unhandled
        # exceptions); SIGTERM itself stays with _on_term above
        monitor.install_flight_recorder(flight, on_sigterm=False)
    except Exception as e:  # noqa: BLE001 — monitor must never kill bench
        print(f"# monitor unavailable: {e}", file=sys.stderr)

    if forced_platform:
        ok, detail = True, f"forced platform {forced_platform}"
    else:
        t_probe0 = time.perf_counter()
        ok, detail = _probe_backend(budget_left())
        if _goodput is not None:
            # tunnel/TPU attach time: its own goodput category, so a
            # probe-blocked round is classifiable (BENCH_r04/r05)
            _goodput.attribute("probe_wait",
                               time.perf_counter() - t_probe0)
    if not ok:
        print(f"# {detail}", file=sys.stderr)
        # children inherit FLAGS_enable_monitor via env and flush their
        # own snapshots to the shared log (appends are line-atomic)
        cpu_ok = _cpu_validate(models, budget_left(),
                               log_path=log if monitor_on else "")
        for m in models:
            line = _error_line(m, detail, cpu_validated=cpu_ok[m])
            print(json.dumps(line), flush=True)
            _emit(log, {"kind": "bench_result", "ts": time.time(),
                        **line})
            results.append(line)
            done.add(m)
        _finalize_summary("complete", reason=detail)
        return

    # Persistent compilation cache: repeat sweep configs skip the
    # tunnel's remote_compile service entirely (the r05 wedge began
    # with a dropped remote_compile response — fewer large compile
    # round-trips is both faster and gentler on the tunnel).
    if os.environ.get("BENCH_COMPILE_CACHE", "1") == "1":
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir",
                              "/tmp/ptn_jax_cache")
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception as e:  # noqa: BLE001 — cache is best-effort
            print(f"# compile cache unavailable: {e}", file=sys.stderr)

    fns = {"bert": bench_bert, "resnet50": bench_resnet50,
           "gpt": bench_gpt, "transformer": bench_transformer,
           "deeplab": bench_deeplab}
    prev_elapsed = None
    for i, m in enumerate(models):
        left = budget_left()
        # stop cleanly between configs: skip the rest once the budget
        # is spent, or when the next config can't plausibly finish in
        # the time remaining (estimated from the previous config)
        if left is not None and (
                left <= 0 or (prev_elapsed is not None
                              and left < 0.8 * prev_elapsed)):
            for skip in models[i:]:
                line = _error_line(
                    skip, f"skipped: time budget exhausted "
                          f"({args.time_budget:.0f}s)")
                print(json.dumps(line), flush=True)
                _emit(log, {"kind": "bench_result", "ts": time.time(),
                            **line})
                results.append(line)
                done.add(skip)
            break
        t0 = time.time()
        try:
            line = fns[m]()
        except Exception as e:  # noqa: BLE001 — artifact must exist
            line = _error_line(m, f"{type(e).__name__}: {e}")
        prev_elapsed = time.time() - t0
        print(json.dumps(line), flush=True)
        _emit(log, {"kind": "bench_result", "ts": time.time(), **line})
        ex = line.get("extra") or {}
        if ex.get("mesh_shape") and ex.get("mesh_devices"):
            # companion ledger record for BENCH_MESH runs: the scaling
            # facts validate_bench_json.py checks and the
            # metrics_report.py '-- sharding --' section renders
            _emit(log, {"kind": "sharded_bench", "ts": time.time(),
                        "metric": line["metric"],
                        "unit": line.get("unit"),
                        "mesh_shape": ex["mesh_shape"],
                        "mesh_axes": ex.get("mesh_axes"),
                        "mesh_devices": ex["mesh_devices"],
                        "per_chip_throughput": ex.get(
                            "tok_s_per_chip",
                            round(line["value"] / ex["mesh_devices"],
                                  1)),
                        "collective_bytes_per_step": ex.get(
                            "collective_bytes_per_step", 0)})
        results.append(line)
        done.add(m)
        _finalize_summary("running")  # artifact parses mid-run too
        if monitor_on:
            try:
                from paddle_tpu import monitor
                monitor.snapshot_to_jsonl(log)
            except Exception as e:  # noqa: BLE001
                print(f"# snapshot failed: {e}", file=sys.stderr)
    if _goodput is not None:
        _goodput.end_run()
        try:
            # goodput_snapshot JSONL record: tools/goodput_report.py
            # renders the category table + waterfall from the bench log
            _goodput.export_snapshot(log)
        except OSError as e:
            print(f"# goodput export failed: {e}", file=sys.stderr)
    _finalize_summary("complete")
    _ledger_and_gate(summary, log, platform_hint=forced_platform)
    try:
        from paddle_tpu import monitor
        if monitor.flight_records():
            monitor.dump_flight_recorder(flight, reason="bench complete")
    except Exception as e:  # noqa: BLE001 — post-mortem is best-effort
        print(f"# flight recorder dump failed: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()

"""Benchmark: BERT-base pretraining step throughput on one TPU chip.

Matches BASELINE.json config 3 ("BERT-base pretraining — tokens/sec/chip").
The whole training step (fwd + vjp-backward + AdamW) is one XLA program
produced by the Executor. vs_baseline = measured MFU / 0.50 (the north-star
">=50% MFU" target; the reference publishes no numeric baseline —
BASELINE.md).

Prints ONE JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def peak_flops_per_chip():
    """bf16 peak for the local chip; v5e = 197 TFLOP/s."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v4" in kind:
        return 275e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    return 197e12


def model_flops_per_token(cfg, seq_len):
    """Matmul flops per token, fwd+bwd (3x fwd): dense 6*N_mat +
    attention 12*L*T*d (scores+context, fwd+bwd)."""
    d, L = cfg.d_model, cfg.n_layers
    n_mat = L * (4 * d * d + 2 * d * cfg.d_ff) + cfg.vocab_size * d
    dense = 6 * n_mat
    attn = 12 * L * seq_len * d
    return dense + attn


def main():
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer

    batch = int(os.environ.get("BENCH_BATCH", "16"))
    seq_len = int(os.environ.get("BENCH_SEQ", "512"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    amp = os.environ.get("BENCH_AMP", "1") == "1"
    use_flash = os.environ.get("BENCH_FLASH", "1") == "1"

    cfg = transformer.bert_base(dropout=0.1, attn_dropout=0.0,
                                use_flash=use_flash)
    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        loss, feeds = transformer.build_train(cfg, batch, seq_len, lr=1e-4,
                                              amp=amp)
        exe = fluid.Executor()
        exe.run(startup)

        rng = np.random.RandomState(0)
        toks = rng.randint(0, cfg.vocab_size,
                           (batch, seq_len)).astype(np.int64)
        feed = {"tokens": toks, "labels": toks}

        # compile + warmup
        exe.run(main_prog, feed=feed, fetch_list=[loss])
        exe.run(main_prog, feed=feed, fetch_list=[loss])

        t0 = time.perf_counter()
        for _ in range(steps):
            lv, = exe.run(main_prog, feed=feed, fetch_list=[loss])
        dt = (time.perf_counter() - t0) / steps

    tokens_per_sec = batch * seq_len / dt
    flops = model_flops_per_token(cfg, seq_len) * batch * seq_len
    mfu = flops / dt / peak_flops_per_chip()
    print(json.dumps({
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.50, 4),
        "extra": {"step_ms": round(dt * 1000, 2), "mfu": round(mfu, 4),
                  "batch": batch, "seq_len": seq_len,
                  "loss": float(np.asarray(lv))},
    }))


if __name__ == "__main__":
    main()

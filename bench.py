"""Benchmark: single-chip training-step throughput on real TPU.

Matches BASELINE.json: the primary metric is BERT-base pretraining
tokens/sec/chip (config 3); BENCH_MODEL=resnet50 measures the ResNet-50
ImageNet config (the north-star MFU workload, config 0). Each step
(fwd + vjp-backward + optimizer) is ONE XLA program produced by the
Executor. vs_baseline = measured MFU / 0.50 (the ">=50% MFU" north
star; the reference publishes no numeric baseline — BASELINE.md).

Prints ONE JSON line for the selected model (default: bert).
BENCH_MODEL selects bert | resnet50 | gpt (causal flash path) |
both (bert + resnet50) | all (all three).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def peak_flops_per_chip():
    """bf16 peak for the local chip; v5e = 197 TFLOP/s."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v4" in kind:
        return 275e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    return 197e12


def model_flops_per_token(cfg, seq_len):
    """Matmul flops per token, fwd+bwd (3x fwd): dense 6*N_mat +
    attention 12*L*T*d (scores+context, fwd+bwd)."""
    d, L = cfg.d_model, cfg.n_layers
    n_mat = L * (4 * d * d + 2 * d * cfg.d_ff) + cfg.vocab_size * d
    dense = 6 * n_mat
    attn = 12 * L * seq_len * d
    return dense + attn


def _timed_steps(exe, prog, feed, loss, steps):
    """Device step time with host/transport latency amortized out.

    The chip may sit behind a remote tunnel where every device→host
    sync costs a full round trip (measured ~70-110 ms here — 2-5x a
    whole training step). Fetching the loss to numpy every iteration
    (the naive loop) therefore measures the network, not the TPU.
    Instead: enqueue `steps` async steps (they serialize on-device via
    the donated state dict), sync ONCE at the end, and subtract one
    measured sync RTT. On a locally attached device rtt ~= 0 and this
    degrades to plain wall-clock timing.
    """
    import jax.numpy as jnp

    # compile + warmup (synced)
    exe.run(prog, feed=feed, fetch_list=[loss])
    x, = exe.run(prog, feed=feed, fetch_list=[loss], return_numpy=False)
    np.asarray(x)  # drain the queue
    np.asarray(jnp.zeros(()) + 1)  # compile the probe expression
    t0 = time.perf_counter()
    # fresh tiny device value: queue is empty and the probe is already
    # compiled, so fetching it is one pure host<->device round trip
    # (np.asarray on an already-fetched array would hit the cached host
    # copy and measure ~0)
    np.asarray(jnp.zeros(()) + 1)
    rtt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(steps):
        x, = exe.run(prog, feed=feed, fetch_list=[loss],
                     return_numpy=False)
    lv = np.asarray(x)
    elapsed = time.perf_counter() - t0
    # never let the RTT subtraction zero out (or flip the sign of) the
    # measurement — a tiny model behind a slow tunnel could otherwise
    # print negative tokens/s
    dt = max(elapsed - rtt, 0.05 * elapsed) / steps
    return dt, lv


def build_bert_bench(batch=None, seq_len=None):
    """Build the BERT pretraining step per the BENCH_* env config.
    Returns (exe, program, scope, feed, loss, cfg) — shared by bench.py
    and tools/profile_step.py so the profiled program is exactly the
    benchmarked one."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer

    batch = batch or int(os.environ.get("BENCH_BATCH", "32"))
    seq_len = seq_len or int(os.environ.get("BENCH_SEQ", "512"))
    amp = os.environ.get("BENCH_AMP", "1") == "1"
    use_flash = os.environ.get("BENCH_FLASH", "1") == "1"
    cfg = transformer.bert_base(dropout=0.1, attn_dropout=0.0,
                                use_flash=use_flash)
    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        loss, feeds = transformer.build_train(cfg, batch, seq_len, lr=1e-4,
                                              amp=amp)
        exe = fluid.Executor()
        exe.run(startup)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, cfg.vocab_size, (batch, seq_len)).astype(np.int64)
    feed = {"tokens": toks, "labels": toks}
    return exe, main_prog, scope, feed, loss, cfg


def build_resnet50_bench(batch=None):
    """ResNet-50 ImageNet step per the BENCH_* env config; same return
    contract as build_bert_bench (cfg slot is None)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    batch = batch or int(os.environ.get("BENCH_BATCH", "64"))
    amp = os.environ.get("BENCH_AMP", "1") == "1"
    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        loss, acc, feeds = resnet.build_train(amp=amp)
        exe = fluid.Executor()
        exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"image": rng.randn(batch, 3, 224, 224).astype(np.float32),
            "label": rng.randint(0, 1000, (batch, 1)).astype(np.int64)}
    return exe, main_prog, scope, feed, loss, None


def bench_bert():
    import paddle_tpu as fluid

    steps = int(os.environ.get("BENCH_STEPS", "20"))
    if "BENCH_FLASH" not in os.environ:
        # unset: probe both attention implementations briefly and run
        # the full measurement with the winner (the framework's job is
        # the fastest correct step, not a fixed kernel choice)
        probes = {}
        for flag in ("1", "0"):
            os.environ["BENCH_FLASH"] = flag
            exe, prog, scope, feed, loss, cfg = build_bert_bench()
            with fluid.scope_guard(scope):
                dt, _ = _timed_steps(exe, prog, feed, loss,
                                     max(4, steps // 4))
            probes[flag] = dt
            exe.close()
        best = min(probes, key=probes.get)
        os.environ["BENCH_FLASH"] = best
    exe, main_prog, scope, feed, loss, cfg = build_bert_bench()
    batch, seq_len = feed["tokens"].shape
    with fluid.scope_guard(scope):
        dt, lv = _timed_steps(exe, main_prog, feed, loss, steps)

    tokens_per_sec = batch * seq_len / dt
    flops = model_flops_per_token(cfg, seq_len) * batch * seq_len
    mfu = flops / dt / peak_flops_per_chip()
    return {
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.50, 4),
        "extra": {"step_ms": round(dt * 1000, 2), "mfu": round(mfu, 4),
                  "batch": batch, "seq_len": seq_len,
                  "loss": float(np.asarray(lv))},
    }


def bench_resnet50():
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    steps = int(os.environ.get("BENCH_STEPS", "20"))
    exe, main_prog, scope, feed, loss, _ = build_resnet50_bench()
    batch = feed["image"].shape[0]
    with fluid.scope_guard(scope):
        dt, lv = _timed_steps(exe, main_prog, feed, loss, steps)

    images_per_sec = batch / dt
    flops = 3 * resnet.flops_per_image() * batch  # fwd + 2x bwd
    mfu = flops / dt / peak_flops_per_chip()
    return {
        "metric": "resnet50_imagenet_images_per_sec_per_chip",
        "value": round(images_per_sec, 1),
        "unit": "images/s",
        "vs_baseline": round(mfu / 0.50, 4),
        "extra": {"step_ms": round(dt * 1000, 2), "mfu": round(mfu, 4),
                  "batch": batch, "loss": float(np.asarray(lv))},
    }


def build_gpt_bench(batch=None, seq_len=None):
    """GPT-small causal-LM step per the BENCH_* env config (third
    headline workload: exercises the causal flash-kernel path)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import gpt

    batch = batch or int(os.environ.get("BENCH_BATCH", "32"))
    seq_len = seq_len or int(os.environ.get("BENCH_SEQ", "512"))
    amp = os.environ.get("BENCH_AMP", "1") == "1"
    use_flash = os.environ.get("BENCH_FLASH", "1") == "1"
    cfg = gpt.gpt_small(dropout=0.1, attn_dropout=0.0,
                        use_flash=use_flash, max_seq_len=seq_len)
    main_prog, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        loss, logits, tokens = gpt.build_train(cfg, batch, seq_len,
                                               lr=3e-4, amp=amp)
        exe = fluid.Executor()
        exe.run(startup)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, cfg.vocab_size, (batch, seq_len)).astype(np.int64)
    return exe, main_prog, scope, {"tokens": toks}, loss, cfg


def bench_gpt():
    import paddle_tpu as fluid

    steps = int(os.environ.get("BENCH_STEPS", "20"))
    exe, main_prog, scope, feed, loss, cfg = build_gpt_bench()
    batch, seq_len = feed["tokens"].shape
    with fluid.scope_guard(scope):
        dt, lv = _timed_steps(exe, main_prog, feed, loss, steps)
    t_eff = seq_len - 1  # in-graph next-token shift
    tokens_per_sec = batch * t_eff / dt
    # causal attention does half the score/context flops: subtract half
    # of the attention term from the shared full-attention accounting
    flops_tok = model_flops_per_token(cfg, t_eff) \
        - 6 * cfg.n_layers * t_eff * cfg.d_model
    flops = flops_tok * batch * t_eff
    mfu = flops / dt / peak_flops_per_chip()
    return {
        "metric": "gpt_small_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.50, 4),
        "extra": {"step_ms": round(dt * 1000, 2), "mfu": round(mfu, 4),
                  "batch": int(batch), "seq_len": int(seq_len),
                  "loss": float(np.asarray(lv))},
    }


def _wait_for_backend():
    """The TPU tunnel can be transiently wedged (UNAVAILABLE backend
    init). Retry for up to BENCH_WAIT_TPU_S seconds (default 600)
    before measuring; on exhaustion proceed and let the real error
    surface."""
    deadline = time.time() + float(os.environ.get("BENCH_WAIT_TPU_S",
                                                  "600"))
    while True:
        try:
            import jax
            jax.devices()
            return
        except RuntimeError as e:
            if time.time() >= deadline:
                print(f"# backend still unavailable after retries: {e}",
                      file=sys.stderr)
                return
            time.sleep(30)


def main():
    _wait_for_backend()
    model = os.environ.get("BENCH_MODEL", "bert")
    if model == "both":
        print(json.dumps(bench_bert()))
        print(json.dumps(bench_resnet50()))
    elif model == "all":
        print(json.dumps(bench_bert()))
        print(json.dumps(bench_resnet50()))
        print(json.dumps(bench_gpt()))
    elif model == "resnet50":
        print(json.dumps(bench_resnet50()))
    elif model == "gpt":
        print(json.dumps(bench_gpt()))
    else:
        print(json.dumps(bench_bert()))


if __name__ == "__main__":
    main()
